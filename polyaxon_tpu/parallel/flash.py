"""Pallas flash attention for the ring body: O(T_local) memory per shard.

The last TPU-native mile of long-context sequence parallelism (SURVEY §5;
the reference platform has no analogue — its compute lived in user
containers).  ``parallel.ring`` rotates K/V blocks around a mesh axis; this
module supplies the *per-block* kernel so the [T_local, T_local] score
matrix never materializes either: scores live in VMEM tiles, the kernel
streams K/V blocks through the MXU with an online-softmax accumulator, and
each block call returns ``(o, lse)`` so the ring loop can merge blocks with
the standard log-sum-exp combine.

Differentiation is handled at the *ring* level (``ring_flash_attention``)
with a custom VJP — the canonical ring-attention backward: a second ring
pass rotates ``(k, v, dk, dv)`` together so each block's gradient
accumulates on whichever device currently holds it and arrives home after a
full cycle, while ``dq`` accumulates locally.  Per-block gradients are two
pallas kernels (dq-pass and dk/dv-pass) using the saved ``lse`` and the
``delta = rowsum(do * o)`` trick, so backward memory is O(T_local) too.

Off-TPU the kernels run in pallas interpret mode — numerically exact and
mesh-compatible, which is how the 8-device virtual-CPU suite verifies ring
+flash numerics and how ``dryrun_multichip`` validates the sharded path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_BIG = -1e30  # mask value; finite so masked rows stay NaN-free


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def _pick_block(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= want (prefers powers of two)."""
    b = min(want, t)
    while t % b:
        b -= 1
    return max(b, 1)


# ---------------------------------------------------------------------------
# Forward block kernel: q[BH,Tq,d] x k,v[BH,Tk,d] -> o[BH,Tq,d] f32, lse f32
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
    *, sm_scale, causal, bq, bk, nk,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)

    # Causal: blocks entirely above the diagonal contribute nothing.
    run = (ki * bk <= qi * bq + bq - 1) if causal else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            s = jnp.where(keep, s, _NEG_BIG)
        m_prev = m_scr[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)  # m_prev=-inf -> 0
        p = jnp.exp(s - m_cur)
        if causal:
            p = jnp.where(keep, p, 0.0)  # rows masked-so-far: m_cur=_NEG_BIG
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[...] = acc[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_cur, m_scr.shape)

    @pl.when(ki == nk - 1)
    def _write():
        l = l_scr[:, :1]
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc[...] / safe).astype(o_ref.dtype)
        # lse rides a lane-replicated [bq, 128] layout: Mosaic requires
        # the last block dim be 128-aligned (or the full array dim), so a
        # [bq]-shaped output cannot lower on real TPUs.
        lse = jnp.where(
            l > 0, m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-38)), -jnp.inf
        )
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def flash_block_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    sm_scale: float,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """One attention block: returns ``(o, lse)`` with o float32-normalized.

    q: [BH, Tq, d]; k, v: [BH, Tk, d].  ``causal`` masks assuming q and k
    share a global offset (the ring's diagonal block).
    """
    if interpret is None:
        interpret = not _on_tpu()
    BH, Tq, d = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, block_q)
    bk = _pick_block(Tk, block_k)
    nq, nk = Tq // bq, Tk // bk
    from jax.experimental.pallas import tpu as pltpu

    scratch = [
        pltpu.VMEM((bq, d), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
    ]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, bq=bq, bk=bk, nk=nk
    )
    o, lse_pad = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, d), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq, 128), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return o, lse_pad[:, :, 0]


# ---------------------------------------------------------------------------
# Backward block kernels (flash-2 style, using saved lse and delta)
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, sm_scale, causal, bq, bk, nk,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (ki * bk <= qi * bq + bq - 1) if causal else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        lse = lse_ref[0][:, :1]  # lane-replicated [bq, 128] input
        p = jnp.exp(s - lse)
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
        dq_acc[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _write():
        dq_ref[0] = dq_acc[...]


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, sm_scale, causal, bq, bk, nq,
):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (ki * bk <= qi * bq + bq - 1) if causal else (qi >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        lse = lse_ref[0][:, :1]  # lane-replicated [bq, 128] input
        p = jnp.exp(s - lse)
        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        do = do_ref[0]
        dv_acc[...] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
        dk_acc[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _write():
        dk_ref[0] = dk_acc[...]
        dv_ref[0] = dv_acc[...]


def flash_block_bwd(
    q, k, v, do, lse, delta, *, causal, sm_scale,
    block_q: int = 1024, block_k: int = 1024, interpret: bool | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Gradients for one block pair: returns ``(dq, dk, dv)`` float32."""
    if interpret is None:
        interpret = not _on_tpu()
    BH, Tq, d = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, block_q)
    bk = _pick_block(Tk, block_k)
    nq, nk = Tq // bq, Tk // bk
    from jax.experimental.pallas import tpu as pltpu

    # Row statistics ride lane-replicated [BH, Tq, 128] (Mosaic block
    # tiling: the last dim must be 128-aligned or the full array dim).
    lse128 = jnp.broadcast_to(lse[:, :, None], (BH, Tq, 128))
    delta128 = jnp.broadcast_to(delta[:, :, None], (BH, Tq, 128))

    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, bq=bq, bk=bk, nk=nk
        ),
        grid=(BH, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((BH, Tq, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse128, delta128)[0]

    # dk/dv pass: grid iterates q blocks innermost for each k block.
    qT_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    kT_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    rowT_spec = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, bq=bq, bk=bk, nq=nq
        ),
        grid=(BH, nk, nq),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec, rowT_spec],
        out_specs=[kT_spec, kT_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, d), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse128, delta128)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Ring-level flash attention with custom VJP (per-shard code, runs inside
# shard_map; cfg = (axis_name, sm_scale, block_q, block_k, interpret))
# ---------------------------------------------------------------------------


def _merge(o, lse, o_b, lse_b):
    """Log-sum-exp combine of two normalized partial attentions."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w_old = jnp.where(jnp.isneginf(lse_new), 0.0, jnp.exp(lse - lse_new))
    w_new = jnp.where(jnp.isneginf(lse_new), 0.0, jnp.exp(lse_b - lse_new))
    o = o * w_old[..., None] + o_b * w_new[..., None]
    return o, lse_new


def _hop_case(i, idx):
    """0 = diagonal (causal), 1 = full block, 2 = skip (future keys)."""
    return jnp.where(i == 0, 0, jnp.where(i <= idx, 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def ring_flash_attention(cfg, q, k, v):
    """Causal ring attention with pallas flash blocks.

    q: [B,Tl,H,d]; k/v: [B,Tl,Hkv,d] with Hkv dividing H (GQA) — the ring
    rotates the UNEXPANDED KV blocks (ppermute payload shrinks by H/Hkv)
    and broadcasts them to the query heads only at each kernel call.
    """
    return _ring_flash_fwd(cfg, q, k, v)[0]


def _bhd(x):
    B, T, H, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, d)


def _unbhd(x, B, H):
    BH, T, d = x.shape
    return x.reshape(B, H, T, d).transpose(0, 2, 1, 3)


def _gqa_expand(x, B, group):
    """[B*Hkv, T, d] → [B*H, T, d] by repeating each KV head ``group``×."""
    if group == 1:
        return x
    BHkv, T, d = x.shape
    return jnp.repeat(x.reshape(B, BHkv // B, T, d), group, axis=1).reshape(
        B * (BHkv // B) * group, T, d
    )


def _gqa_reduce(dx, B, group):
    """Transpose of :func:`_gqa_expand`: sum query-head grads per KV head."""
    if group == 1:
        return dx
    BH, T, d = dx.shape
    return (
        dx.reshape(B, BH // B // group, group, T, d)
        .sum(axis=2)
        .reshape(BH // group, T, d)
    )


def _ring_flash_fwd(cfg, q, k, v):
    axis_name, sm_scale, block_q, block_k, interpret = cfg
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, d = q.shape
    group = H // k.shape[2]
    qf, kf, vf = _bhd(q), _bhd(k), _bhd(v)
    perm = [(j, (j + 1) % n) for j in range(n)]

    o0 = jnp.zeros((B * H, Tl, d), jnp.float32)
    lse0 = jnp.full((B * H, Tl), -jnp.inf, jnp.float32)

    def block(causal):
        def run(args):
            o, lse, kc, vc = args
            o_b, lse_b = flash_block_fwd(
                qf,
                _gqa_expand(kc, B, group),
                _gqa_expand(vc, B, group),
                causal=causal, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k, interpret=interpret,
            )
            return _merge(o, lse, o_b, lse_b)
        return run

    def body(i, carry):
        o, lse, kc, vc = carry
        o, lse = lax.switch(
            _hop_case(i, idx),
            [block(True), block(False), lambda a: (a[0], a[1])],
            (o, lse, kc, vc),
        )
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        return o, lse, kc, vc

    o, lse, _, _ = lax.fori_loop(0, n, body, (o0, lse0, kf, vf))
    out = _unbhd(o, B, H).astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(cfg, res, do):
    axis_name, sm_scale, block_q, block_k, interpret = cfg
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, d = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qf, kf, vf = _bhd(q), _bhd(k), _bhd(v)
    dof = _bhd(do.astype(q.dtype))
    of = _bhd(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    perm = [(j, (j + 1) % n) for j in range(n)]

    dq0 = jnp.zeros((B * H, Tl, d), jnp.float32)
    dkv0 = jnp.zeros((B * Hkv, Tl, d), jnp.float32)

    def block(causal):
        def run(args):
            kc, vc = args
            dq_i, dk_i, dv_i = flash_block_bwd(
                qf,
                _gqa_expand(kc, B, group),
                _gqa_expand(vc, B, group),
                dof, lse, delta, causal=causal,
                sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                interpret=interpret,
            )
            return dq_i, _gqa_reduce(dk_i, B, group), _gqa_reduce(dv_i, B, group)
        return run

    def skip(args):
        return dq0, dkv0, dkv0

    def body(i, carry):
        dq, kc, vc, dkc, dvc = carry
        dq_i, dk_i, dv_i = lax.switch(
            _hop_case(i, idx), [block(True), block(False), skip], (kc, vc)
        )
        dq = dq + dq_i
        # dk/dv accumulators travel WITH their k/v block: after the full
        # cycle of n hops each block (and its gradient) is home again.
        kc, vc, dkc, dvc = lax.ppermute(
            (kc, vc, dkc + dk_i, dvc + dv_i), axis_name, perm
        )
        return dq, kc, vc, dkc, dvc

    dq, _, _, dk, dv = lax.fori_loop(0, n, body, (dq0, kf, vf, dkv0, dkv0))
    return (
        _unbhd(dq, B, H).astype(q.dtype),
        _unbhd(dk, B, Hkv).astype(k.dtype),
        _unbhd(dv, B, Hkv).astype(v.dtype),
    )


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# ---------------------------------------------------------------------------
# Single-device causal flash (no ring): the same block kernels over the
# full sequence, with the standard flash VJP. Measured 1.9x the jax-bundled
# pallas flash kernel in full train steps at T=8192 on v5e (8.4k vs 4.4k
# tok/s — docs/bench-notes.md), so this is the kernel behind
# attention_impl="flash" everywhere.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def flash_attention(cfg, q, k, v):
    """Causal flash attention. q/k/v: [B,T,H,d]; cfg=(sm_scale, block_q,
    block_k, interpret)."""
    return _flash_fwd(cfg, q, k, v)[0]


def _flash_fwd(cfg, q, k, v):
    sm_scale, block_q, block_k, interpret = cfg
    B, T, H, d = q.shape
    o, lse = flash_block_fwd(
        _bhd(q), _bhd(k), _bhd(v), causal=True, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    out = _unbhd(o, B, H).astype(q.dtype)
    return out, (q, k, v, out, lse)


def _flash_bwd(cfg, res, do):
    sm_scale, block_q, block_k, interpret = cfg
    q, k, v, out, lse = res
    B, T, H, d = q.shape
    qf, kf, vf = _bhd(q), _bhd(k), _bhd(v)
    dof = _bhd(do.astype(q.dtype))
    delta = jnp.sum(
        dof.astype(jnp.float32) * _bhd(out).astype(jnp.float32), axis=-1
    )
    dq, dk, dv = flash_block_bwd(
        qf, kf, vf, dof, lse, delta, causal=True, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return (
        _unbhd(dq, B, H).astype(q.dtype),
        _unbhd(dk, B, H).astype(k.dtype),
        _unbhd(dv, B, H).astype(v.dtype),
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
