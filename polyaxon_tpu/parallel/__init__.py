from polyaxon_tpu.parallel.axes import (
    AxisRules,
    logical_to_spec,
    tree_specs,
    tree_shardings,
    with_logical_constraint,
)
from polyaxon_tpu.parallel.templates import StrategyTemplate, template_for

__all__ = [
    "AxisRules",
    "StrategyTemplate",
    "logical_to_spec",
    "template_for",
    "tree_specs",
    "tree_shardings",
    "with_logical_constraint",
]
