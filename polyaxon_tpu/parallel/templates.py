"""Parallelism strategies as sharding templates.

Each strategy the spec DSL names (``environment.topology.strategy``) is a
:class:`StrategyTemplate`: a logical→mesh axis-rule set plus runtime
switches (ring attention, pipeline schedule).  This is the capability the
reference implemented as four env-var dialects (``polypod/tensorflow.py:
193-203`` TF_CONFIG, ``pytorch.py:139-157`` MASTER_ADDR, ``mxnet.py:19-35``
DMLC, ``horovod.py:143-166`` mpirun) — except those could only express data
parallelism; here DP/FSDP/TP/PP/SP-ring/Ulysses/EP are first-class because
a strategy is just an axis mapping consumed by pjit (SURVEY §2.8).

Logical-axis vocabulary (shared with ``polyaxon_tpu.models``):

==============  ============================================================
``vocab``       embedding table rows / output head columns
``embed``       the model (residual-stream) dimension of parameters
``heads``       attention-head dimension of parameters
``head_dim``    per-head feature dim (never sharded)
``mlp``         feed-forward hidden dimension of parameters
``layers``      stacked-layer leading dimension (pipeline stages)
``experts``     MoE expert dimension
``batch``       activation batch dimension
``seq``         activation sequence dimension
``attn_heads``  activation head dimension *inside* attention (Ulysses
                switches this to the sequence mesh axis: XLA inserts the
                all-to-alls)
==============  ============================================================
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from polyaxon_tpu.exceptions import RuntimeLayerError
from polyaxon_tpu.parallel.axes import AxisRules

#: Mesh axes over which the *batch* may be sharded (data-like axes).
DATA_AXES = ("replica", "data", "fsdp")


@dataclass(frozen=True)
class StrategyTemplate:
    """Everything the runtime needs to apply one parallelism strategy."""

    name: str
    #: logical axis -> mesh axis (or tuple / None) for params AND activations
    rules: Dict[str, Any]
    #: mesh axes sharding the global-batch dimension
    batch_axes: Tuple[str, ...]
    #: attention runs the ring kernel over this mesh axis (sp_ring)
    ring_axis: Optional[str] = None
    #: Ulysses sequence axis: the flash path swaps seq↔heads with explicit
    #: all-to-alls in a shard_map (``parallel/ulysses.py``); the dense path
    #: keeps the GSPMD attn_heads-constraint formulation
    ulysses_axis: Optional[str] = None
    #: layers are pipeline stages over this mesh axis (pp)
    pipeline_axis: Optional[str] = None
    #: microbatch count for the pipeline schedule
    num_microbatches: int = 1
    #: composition mode: the pipeline shard_map is manual over
    #: ``pipeline_axis`` ONLY, leaving data/tensor axes to GSPMD so the
    #: block's sharding constraints stay live inside stages (dp×tp×pp)
    pipeline_composed: bool = False
    options: Dict[str, Any] = field(default_factory=dict)

    def batch_spec(self):
        from jax.sharding import PartitionSpec

        axes = self.batch_axes
        if not axes:
            return PartitionSpec()
        return PartitionSpec(axes if len(axes) > 1 else axes[0])


def _data_axes(mesh_axes: Dict[str, int]) -> Tuple[str, ...]:
    return tuple(a for a in DATA_AXES if a in mesh_axes and mesh_axes[a] > 1)


def template_for(
    strategy: str,
    mesh_axes: Dict[str, int],
    options: Optional[Dict[str, Any]] = None,
) -> StrategyTemplate:
    """Resolve a named strategy against a concrete mesh."""
    options = dict(options or {})
    data = _data_axes(mesh_axes)
    batch_rules: Dict[str, Any] = {"batch": data if data else None}

    def fsdp_axis() -> Optional[str]:
        for a in ("fsdp", "data"):
            if a in mesh_axes and mesh_axes[a] > 1:
                return a
        return None

    if strategy == "ddp":
        return StrategyTemplate("ddp", batch_rules, data, options=options)

    if strategy == "fsdp":
        ax = fsdp_axis()
        rules = {**batch_rules, "embed": ax}
        return StrategyTemplate("fsdp", rules, data, options=options)

    if strategy == "tp":
        rules = {
            **batch_rules,
            "heads": "tensor",
            "mlp": "tensor",
            "vocab": "tensor",
            "experts": "tensor",
            "attn_heads": "tensor",
        }
        if "tensor" not in mesh_axes:
            raise RuntimeLayerError("tp strategy needs a 'tensor' mesh axis")
        return StrategyTemplate("tp", rules, data, options=options)

    if strategy == "tp_dp":
        if "tensor" not in mesh_axes:
            raise RuntimeLayerError("tp_dp strategy needs a 'tensor' mesh axis")
        rules = {
            **batch_rules,
            "embed": fsdp_axis(),
            "heads": "tensor",
            "mlp": "tensor",
            "vocab": "tensor",
            "attn_heads": "tensor",
        }
        return StrategyTemplate("tp_dp", rules, data, options=options)

    if strategy == "pp":
        if "pipeline" not in mesh_axes:
            raise RuntimeLayerError("pp strategy needs a 'pipeline' mesh axis")
        rules = {**batch_rules, "layers": "pipeline"}
        return StrategyTemplate(
            "pp",
            rules,
            data,
            pipeline_axis="pipeline",
            num_microbatches=int(options.get("num_microbatches", mesh_axes["pipeline"])),
            options=options,
        )

    if strategy == "pp_tp":
        # 3-axis composition: batch over data, attention/MLP over tensor,
        # layers over pipeline — the scaling-book "combine all three"
        # recipe as one template.
        for ax in ("pipeline", "tensor"):
            if ax not in mesh_axes:
                raise RuntimeLayerError(f"pp_tp strategy needs a '{ax}' mesh axis")
        rules = {
            **batch_rules,
            "layers": "pipeline",
            "heads": "tensor",
            "mlp": "tensor",
            "vocab": "tensor",
            "attn_heads": "tensor",
        }
        return StrategyTemplate(
            "pp_tp",
            rules,
            data,
            pipeline_axis="pipeline",
            pipeline_composed=True,
            num_microbatches=int(
                options.get("num_microbatches", mesh_axes["pipeline"])
            ),
            options=options,
        )

    if strategy == "sp_ring":
        if "sequence" not in mesh_axes:
            raise RuntimeLayerError("sp_ring strategy needs a 'sequence' mesh axis")
        rules = {**batch_rules, "seq": "sequence"}
        return StrategyTemplate(
            "sp_ring", rules, data, ring_axis="sequence", options=options
        )

    if strategy == "ulysses":
        if "sequence" not in mesh_axes:
            raise RuntimeLayerError("ulysses strategy needs a 'sequence' mesh axis")
        # Outside attention the sequence is sharded; inside attention the
        # heads are — annotating both lets XLA insert the two all-to-alls
        # (DeepSpeed-Ulysses, expressed as sharding constraints). With
        # flash attention the all-to-alls go explicit instead
        # (ulysses_axis → parallel/ulysses.py) since GSPMD can't partition
        # a pallas call.
        rules = {**batch_rules, "seq": "sequence", "attn_heads": "sequence"}
        return StrategyTemplate(
            "ulysses", rules, data, ulysses_axis="sequence", options=options
        )

    if strategy == "ep":
        if "expert" not in mesh_axes:
            raise RuntimeLayerError("ep strategy needs an 'expert' mesh axis")
        rules = {**batch_rules, "experts": "expert", "embed": fsdp_axis()}
        return StrategyTemplate("ep", rules, data, options=options)

    if strategy == "custom":
        rules = dict(options.get("rules", {}))
        rules.setdefault("batch", data if data else None)
        return StrategyTemplate(
            "custom",
            rules,
            tuple(options.get("batch_axes", data)),
            ring_axis=options.get("ring_axis"),
            pipeline_axis=options.get("pipeline_axis"),
            num_microbatches=int(options.get("num_microbatches", 1)),
            options=options,
        )

    raise RuntimeLayerError(f"Unknown strategy {strategy!r}")
