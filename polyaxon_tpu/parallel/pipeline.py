"""Pipeline parallelism: stacked transformer layers as GPipe stages.

The reference's only notion of "pipeline" is workflow DAGs (``polyflow/``);
model pipeline parallelism has no analogue there (SURVEY §2.8).  TPU-native
design: the model's stacked-layer leading axis is sharded over the
``pipeline`` mesh axis (each device holds L/S contiguous layers), and a
``shard_map`` runs the GPipe schedule — microbatches march through stages,
activations hop stage→stage on one ICI link via ``lax.ppermute``.  The
schedule is a static ``fori_loop`` of M + S - 1 ticks, fully compiled; no
host round-trips.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from polyaxon_tpu.exceptions import RuntimeLayerError


def _pp_body(
    x: jax.Array,
    positions: jax.Array,
    layers: Any,
    *,
    block: Callable,
    axis: str,
    n_micro: int,
    aux_fn: Any = None,
    batch_axis_names: Tuple[str, ...] = (),
    stage_ids: Any = None,
    unroll: bool = False,
):
    """Per-device GPipe schedule. x: [B_local, T, D]; layers: local stages.

    ``aux_fn(aux) -> scalar`` (optional) reduces a block's per-layer aux
    output (e.g. MoE gate statistics) to a scalar loss; the schedule
    accumulates it only on a stage's VALID ticks — bubble ticks run the
    body on stale state and must not pollute the sum.

    ``stage_ids`` (optional [1] int array, P(axis)-sharded from a global
    arange) replaces ``lax.axis_index``: under a PARTIAL-manual shard_map
    the old jax line lowers axis_index to an XLA PartitionId op, which the
    SPMD partitioner rejects for the remaining auto axes.

    ``unroll`` statically unrolls the schedule and the per-stage layer
    scan (Python loops, no ``while`` in the HLO), and routes the
    stage→stage hop through ``psum`` instead of ``ppermute``.  Both are
    required on the old jax line's PARTIAL-manual path: the transpose of
    any while loop leaves its scalar carries ``{replicated}`` amid
    manual-subgroup neighbors, and XLA's sharding propagation never
    assigns a manual-subgroup sharding to a ``collective-permute`` —
    either way the SPMD partitioner fatals on the mix.  The psum hop
    all-reduces a one-hot-stacked send ([S, ...]) and picks slot
    stage-1 locally: S× the ppermute payload, acceptable at real stage
    counts.  Tick count is M + S - 1 and stages hold L/S layers, so the
    unrolled body stays small at realistic microbatch counts.
    """
    S = lax.psum(1, axis)
    stage = lax.axis_index(axis) if stage_ids is None else stage_ids[0]
    B, T, D = x.shape
    mb = x.reshape(n_micro, B // n_micro, T, D)
    pos_mb = positions.reshape(n_micro, B // n_micro, T)
    perm = [(j, (j + 1) % S) for j in range(S)]

    def run_stage(inp, pos):
        def scan_body(c, layer):
            out, aux = block(c, pos, layer)
            return out, (aux_fn(aux) if aux_fn is not None else 0.0)

        if unroll:
            n_local = jax.tree.leaves(layers)[0].shape[0]
            out, auxes = inp, []
            for li in range(n_local):
                out, a = scan_body(out, jax.tree.map(lambda w: w[li], layers))
                auxes.append(a)
            layer_aux = jnp.stack(auxes) if aux_fn is not None else None
        else:
            out, layer_aux = lax.scan(scan_body, inp, layers)
        return out, jnp.mean(layer_aux) if aux_fn is not None else 0.0

    outputs = jnp.zeros_like(mb)
    state = jnp.zeros_like(mb[0])
    # The aux rides as shape [1], never a true scalar: old-jax shard_map
    # mishandles rank-0 values crossing the manual boundary under AD (its
    # scalar-residual promotion loses track through partial eval, and the
    # transpose then stages a rank-0 cotangent with sharded out-names).
    # A singleton axis sidesteps the whole class; callers squeeze it off.
    aux_acc = jnp.zeros((1,), jnp.float32)

    def tick(i, carry):
        outputs, state, aux_acc = carry
        feed = jnp.clip(i, 0, n_micro - 1)
        inp = jnp.where(
            stage == 0, lax.dynamic_index_in_dim(mb, feed, 0, keepdims=False), state
        )
        pos = lax.dynamic_index_in_dim(pos_mb, feed, 0, keepdims=False)
        # Positions are identical across microbatches for standard LM
        # batches; stage>0 reuses the fed index's positions safely.
        out, stage_aux = run_stage(inp, pos)
        # Stage s processes real microbatches exactly on ticks [s, s+M).
        valid = (i >= stage) & (i < stage + n_micro)
        aux_acc = aux_acc + jnp.where(valid, stage_aux, 0.0)
        j = i - (S - 1)
        jc = jnp.clip(j, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(outputs, jc, 0, keepdims=False)
        val = jnp.where((stage == S - 1) & (j >= 0), out, cur)
        outputs = lax.dynamic_update_index_in_dim(outputs, val, jc, 0)
        if unroll:
            basis = (jnp.arange(S) == stage).astype(jnp.float32)
            stacked = lax.psum(
                basis.reshape((S,) + (1,) * out.ndim)
                * out[None].astype(jnp.float32),
                axis,
            )
            state = lax.dynamic_index_in_dim(
                stacked, (stage - 1) % S, 0, keepdims=False
            ).astype(out.dtype)
        else:
            state = lax.ppermute(out, axis, perm)
        return outputs, state, aux_acc

    carry = (outputs, state, aux_acc)
    if unroll:
        for i in range(n_micro + S - 1):
            carry = tick(i, carry)
        outputs, _, aux_acc = carry
    else:
        outputs, _, aux_acc = lax.fori_loop(0, n_micro + S - 1, tick, carry)
    # Only the last stage holds real outputs; broadcast over the pipeline
    # axis so downstream (final norm + unembed) sees replicated activations.
    # The psum rides f32: a bf16 all-reduce over a manual axis inside a
    # PARTIAL-manual shard_map hard-crashes XLA CPU ("Invalid binary
    # instruction opcode copy"), and the one-pass cast on the final
    # activations is noise. (Full-manual pp doesn't hit the bug; the shared
    # body takes the safe path for both.)
    out_dtype = outputs.dtype
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, 0.0).astype(jnp.float32), axis
    ).astype(out_dtype)
    # Mean over stages (each holds L/S layers) and microbatches; the aux
    # claims replication in out_specs, so it must also be averaged over any
    # batch-sharding axes (each data shard saw different tokens).
    aux = lax.psum(aux_acc, axis) / (S * n_micro)
    if batch_axis_names:
        aux = lax.pmean(aux, batch_axis_names)
    return outputs.reshape(B, T, D), aux  # aux: [1], squeezed by wrappers


def pipeline_scan_composed(
    block: Callable,
    x: jax.Array,
    positions: jax.Array,
    stacked_layers: Any,
    mesh,
    *,
    axis: str = "pipeline",
    num_microbatches: int = 1,
    aux_fn: Any = None,
) -> Tuple[jax.Array, jax.Array]:
    """GPipe over ``axis`` with every OTHER mesh axis left to GSPMD.

    The composition mode (dp×tp×pp): ``jax.shard_map`` is manual over the
    pipeline axis only, so inside each stage the block's logical sharding
    constraints stay live and XLA shards attention/MLP over ``tensor`` and
    the batch over ``data`` exactly as in the non-pipelined path.  Layer
    stacks are manually split over stages (P(axis) leading dim) while their
    tensor-sharded trailing dims ride through as auto axes.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis]
    n_layers = jax.tree.leaves(stacked_layers)[0].shape[0]
    if n_layers % n_stages:
        raise RuntimeLayerError(
            f"{n_layers} layers not divisible into {n_stages} pipeline stages"
        )
    if x.shape[0] % num_microbatches:
        raise RuntimeLayerError(
            f"Global batch {x.shape[0]} not divisible by "
            f"{num_microbatches} microbatches"
        )
    layer_spec = jax.tree.map(lambda _: P(axis), stacked_layers)
    x_dtype = x.dtype
    # Old jax: the transpose of ANY while loop (fori_loop/scan) inside a
    # partial-manual region leaves scalar loop carries {replicated} amid
    # manual-subgroup neighbors and the SPMD partitioner fatals — unroll
    # the schedule statically there.  New jax handles whiles fine.
    unroll = not hasattr(jax, "shard_map")

    def body_f32(x32, positions, layers, stage_ids):
        # The region boundary rides f32: XLA CPU hard-crashes on a bf16
        # all-reduce over a manual axis inside a PARTIAL-manual shard_map
        # ("Invalid binary instruction opcode copy") — and AD generates
        # exactly that psum for the cotangent of the replicated-in x.
        # Compute stays in the model dtype inside the body.
        out, aux = _pp_body(
            x32.astype(x_dtype),
            positions,
            layers,
            block=block,
            axis=axis,
            n_micro=num_microbatches,
            aux_fn=aux_fn,
            # Auto axes are GSPMD-global inside the body: the aux scalar is
            # already a full-batch value, no pmean over data needed.
            batch_axis_names=(),
            stage_ids=stage_ids,
            unroll=unroll,
        )
        return out.astype(jnp.float32), aux

    from polyaxon_tpu.parallel.shmap import shard_map

    fn = shard_map(
        body_f32,
        mesh=mesh,
        in_specs=(P(), P(), layer_spec, P(axis)),
        out_specs=(P(), P()),
        axis_names={axis},
        check_vma=False,
    )
    stage_ids = jnp.arange(n_stages, dtype=jnp.int32)
    out, aux = fn(x.astype(jnp.float32), positions, stacked_layers, stage_ids)
    return out.astype(x_dtype), aux[0]


def pipeline_scan(
    block: Callable,
    x: jax.Array,
    positions: jax.Array,
    stacked_layers: Any,
    mesh,
    *,
    axis: str = "pipeline",
    num_microbatches: int = 1,
    batch_axes: Union[str, Tuple[str, ...], None] = None,
    aux_fn: Any = None,
) -> Tuple[jax.Array, jax.Array]:
    """Drop-in replacement for the layer ``lax.scan``, pipelined over ``axis``.

    ``block(x, positions, layer) -> (x, aux)`` is the same body the dense
    path scans. The stacked ``layers`` leading dim must divide by the
    pipeline axis size, and the local batch by ``num_microbatches``.
    Returns ``(outputs, aux_scalar)`` — aux is the mean of
    ``aux_fn(block_aux)`` over layers and microbatches (0.0 without aux_fn),
    which is how MoE's load-balancing loss crosses the shard_map boundary.
    """
    from jax.sharding import PartitionSpec as P

    from polyaxon_tpu.parallel.shmap import shard_map

    n_stages = mesh.shape[axis]
    n_layers = jax.tree.leaves(stacked_layers)[0].shape[0]
    if n_layers % n_stages:
        raise RuntimeLayerError(
            f"{n_layers} layers not divisible into {n_stages} pipeline stages"
        )
    batch = x.shape[0]
    import numpy as np

    data_size = int(
        np.prod([mesh.shape[a] for a in (batch_axes or ()) if a in mesh.shape])
        if not isinstance(batch_axes, str)
        else mesh.shape.get(batch_axes, 1)
    )
    local_batch = batch // max(1, data_size)
    if local_batch % num_microbatches:
        raise RuntimeLayerError(
            f"Local batch {local_batch} not divisible by {num_microbatches} microbatches"
        )

    x_spec = P(batch_axes, None, None)
    pos_spec = P(batch_axes, None)
    layer_spec = jax.tree.map(lambda _: P(axis), stacked_layers)
    batch_axis_names = (
        (batch_axes,)
        if isinstance(batch_axes, str)
        else tuple(a for a in (batch_axes or ()) if a in mesh.shape)
    )
    fn = shard_map(
        partial(
            _pp_body,
            block=block,
            axis=axis,
            n_micro=num_microbatches,
            aux_fn=aux_fn,
            batch_axis_names=batch_axis_names,
        ),
        mesh=mesh,
        in_specs=(x_spec, pos_spec, layer_spec),
        out_specs=(x_spec, P()),
        check_vma=False,
    )
    out, aux = fn(x, positions, stacked_layers)
    return out, aux[0]
