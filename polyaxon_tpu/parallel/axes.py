"""Logical-axis sharding: name tensor dimensions, map names to mesh axes.

The TPU-native replacement for the reference's per-framework rendezvous
recipes (``polypod/{tensorflow,pytorch,horovod,mxnet}.py`` — which only ever
expressed *data* parallelism as env vars): every parameter and activation
carries a tuple of *logical* axis names (``("embed", "mlp")``), and a
parallelism strategy is nothing but a mapping from logical names to mesh
axes (``{"mlp": "tensor"}``).  XLA then inserts the collectives.  This is
the idiomatic jax/pjit design (same shape as t5x/flax logical partitioning,
re-implemented here without those deps) and is what lets one model
definition serve ddp/fsdp/tp/pp/sp/ep unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Union

from polyaxon_tpu.exceptions import RuntimeLayerError

#: logical axis name -> mesh axis (str), tuple of mesh axes, or None (replicate)
AxisRules = Mapping[str, Union[str, Tuple[str, ...], None]]

LogicalAxes = Tuple[str, ...]


def logical_to_spec(axes: Sequence[str], rules: AxisRules, mesh_axes=None):
    """Turn one tensor's logical axes into a ``PartitionSpec``.

    ``mesh_axes`` (the mesh's axis->size map) is optional; when given, rules
    that point at axes absent from the mesh degrade to replication — so one
    template works on smaller meshes (e.g. tp rules on a mesh with no
    ``tensor`` axis).
    """
    from jax.sharding import PartitionSpec

    entries = []
    used: set = set()
    for name in axes:
        target = rules.get(name) if name is not None else None
        if target is None:
            entries.append(None)
            continue
        parts = (target,) if isinstance(target, str) else tuple(target)
        if mesh_axes is not None:
            parts = tuple(p for p in parts if p in mesh_axes)
        parts = tuple(p for p in parts if p not in used)
        used.update(parts)
        if not parts:
            entries.append(None)
        elif len(parts) == 1:
            entries.append(parts[0])
        else:
            entries.append(parts)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def tree_specs(axes_tree: Any, rules: AxisRules, mesh_axes=None):
    """Map :func:`logical_to_spec` over a pytree of logical-axes tuples."""
    import jax

    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules, mesh_axes),
        axes_tree,
        # A leaf is one tensor's logical-axes tuple; entries may be None
        # (explicitly-replicated dims).
        is_leaf=lambda x: isinstance(x, tuple)
        and all(e is None or isinstance(e, str) for e in x),
    )


def tree_shardings(mesh, spec_tree: Any):
    """PartitionSpec pytree -> NamedSharding pytree for ``mesh``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def with_logical_constraint(
    x, axes: Sequence[str], rules: AxisRules, mesh=None
):
    """``lax.with_sharding_constraint`` by logical names (inside jit).

    No-op outside a mesh context — model code stays runnable single-device.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        try:
            mesh = jax.sharding.get_abstract_mesh()  # jax>=0.4.35
        except Exception:
            mesh = None
        if mesh is None or getattr(mesh, "empty", False):
            return x
    spec = logical_to_spec(axes, rules, dict(getattr(mesh, "shape", {}) or {}))
    if getattr(mesh, "_any_axis_manual", False):  # inside shard_map
        return x
    if isinstance(mesh, jax.sharding.Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def validate_rules(rules: AxisRules, mesh_axes: Dict[str, int]) -> None:
    """Reject rules that reference axes the mesh doesn't have (strict mode)."""
    for logical, target in rules.items():
        if target is None:
            continue
        parts = (target,) if isinstance(target, str) else target
        missing = [p for p in parts if p not in mesh_axes]
        if missing:
            raise RuntimeLayerError(
                f"Rule {logical!r} -> {target!r} references mesh axes {missing} "
                f"not present in {list(mesh_axes)}"
            )
