"""Ulysses sequence parallelism with flash attention per head shard.

The GSPMD formulation (``templates.py``: constrain ``attn_heads`` to the
sequence axis and let XLA insert the all-to-alls) is elegant but pins
attention to XLA's dense path — a pallas call is a custom call GSPMD
cannot partition, so long-context Ulysses paid O(T²) score memory while
the ring had the flash kernel.  This module is the manual twin: an
explicit ``shard_map`` whose body performs the two DeepSpeed-Ulysses
all-to-alls itself (seq-sharded → head-sharded and back, each one ICI
all-to-all) and runs the framework's flash kernel (``parallel/flash.py``)
over the FULL sequence per head shard — O(T) memory, same numerics.

Autodiff needs no custom VJP here: ``lax.all_to_all`` is linear (its
transpose is the reverse all-to-all) and the flash call carries its own
flash-2 VJP, so gradients compose through the shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple, Union

import jax
from jax import lax

from polyaxon_tpu.parallel.flash import _on_tpu, flash_attention


def _ulysses_body(q, k, v, *, axis_name, cfg):
    """Per-shard body. q/k/v: [B, T_local, H, d] (contiguous seq shards)."""
    # seq-sharded → head-sharded: split the heads axis over the group,
    # concatenate the sequence axis (one all-to-all each).
    swap = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qh, kh, vh = swap(q), swap(k), swap(v)  # [B, T, H/n, d]
    o = flash_attention(cfg, qh, kh, vh)
    # head-sharded → seq-sharded (the reverse all-to-all).
    return lax.all_to_all(
        o, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    seq_axis: str,
    batch_axes: Union[str, Tuple[str, ...], None] = None,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jax.Array:
    """Global-view entry: q/k/v [B, T, H, d] with T sharded on ``seq_axis``
    and H divisible by the axis size."""
    from jax.sharding import PartitionSpec as P

    from polyaxon_tpu.parallel.shmap import shard_map

    n = mesh.shape[seq_axis]
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"Ulysses needs heads ({H}) divisible by the '{seq_axis}' axis ({n})"
        )
    d = q.shape[-1]
    cfg = (d**-0.5, block_q, block_k, not _on_tpu())
    spec = P(batch_axes, seq_axis, None, None)
    fn = shard_map(
        partial(_ulysses_body, axis_name=seq_axis, cfg=cfg),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
