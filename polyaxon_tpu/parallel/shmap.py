"""``shard_map`` across jax versions.

Newer jax exposes ``jax.shard_map(..., axis_names=..., check_vma=...)``;
the older line ships ``jax.experimental.shard_map.shard_map`` whose
equivalents are ``auto`` (the COMPLEMENT of axis_names) and
``check_rep``.  Callers use the new surface; this translates down when
the top-level name is missing.

The old line also needs three targeted repairs, applied once on first
use (each is the fix that later landed upstream, made from outside):

* identity replication rules for the ``name`` primitive (emitted by
  ``jax.checkpoint`` save_only_these_names policies) and for
  ``sharding_constraint`` — without them ``check_rep=True`` rejects any
  body that remats or constrains shardings;
* partial-eval residual out-names restricted to the MANUAL axes.  The
  old ``_shard_map_partial_eval`` names residuals over every mesh axis,
  so under a PARTIAL-manual region (``auto`` nonempty) residual
  boundary shardings mention auto axes and the XLA SPMD partitioner
  fatals on a manual-subgroup mismatch.
"""

from __future__ import annotations

from typing import Any, Optional, Set

_OLD_JAX_PATCHED = False


def _patch_old_shard_map() -> None:
    """One-time repairs to jax.experimental.shard_map (old jax only)."""
    global _OLD_JAX_PATCHED
    if _OLD_JAX_PATCHED:
        return
    _OLD_JAX_PATCHED = True
    from jax.experimental import shard_map as _sm

    # ``name`` and ``sharding_constraint`` are pure pass-throughs, so the
    # standard identity rules are exact.  setdefault semantics make
    # re-registration a no-op.
    try:
        from jax._src.ad_checkpoint import name_p

        _sm.register_standard_check(name_p)
        _sm.register_standard_rewrite(name_p)
    except ImportError:  # pragma: no cover - layout drift on other versions
        pass
    try:
        from jax._src.pjit import sharding_constraint_p

        _sm.register_standard_check(sharding_constraint_p)
        _sm.register_standard_rewrite(sharding_constraint_p)
    except ImportError:  # pragma: no cover - layout drift on other versions
        pass

    # Residual naming: _shard_map_partial_eval receives ``auto`` but
    # computes its residual names via _all_mesh_names_except_spmd(mesh),
    # which ignores it.  Thread the active ``auto`` through a stack so the
    # helper can subtract it — exactly what newer jax's
    # _all_newly_manual_mesh_names does.
    try:
        from jax._src.interpreters import partial_eval as _pe

        _orig_pe = _sm._shard_map_partial_eval
        _orig_names = _sm._all_mesh_names_except_spmd
        _auto_stack: list = []

        def _names_minus_auto(mesh, trace=None):
            names = _orig_names(mesh, trace)
            if _auto_stack and _auto_stack[-1]:
                names = tuple(n for n in names if n not in _auto_stack[-1])
            return names

        def _partial_eval_with_auto(
            trace, prim, f, tracers, mesh, in_names, out_names_thunk,
            check_rep, rewrite, auto,
        ):
            _auto_stack.append(auto)
            try:
                return _orig_pe(
                    trace, prim, f, tracers, mesh, in_names, out_names_thunk,
                    check_rep, rewrite, auto,
                )
            finally:
                _auto_stack.pop()

        _sm._all_mesh_names_except_spmd = _names_minus_auto
        _sm._shard_map_partial_eval = _partial_eval_with_auto
        _pe.JaxprTrace.process_shard_map = _partial_eval_with_auto
    except (ImportError, AttributeError):  # pragma: no cover - layout drift
        pass


def shard_map(
    f,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    axis_names: Optional[Set[str]] = None,
    check_vma: bool = False,
):
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental import shard_map as _sm

    _patch_old_shard_map()
    manual = set(axis_names) if axis_names is not None else set(mesh.axis_names)
    auto = frozenset(mesh.axis_names) - manual
    # check_rep maps from check_vma (both gate replication tracking; the
    # old checker also rejects valid programs, e.g. scan carries mixing
    # known/unknown replication, so callers here all pass False).  With it
    # off the transpose takes the defensive-psum path, which is correct as
    # long as no rank-0 value crosses the boundary — see _pp_body's
    # rank-1 aux.
    return _sm.shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        auto=auto,
    )
