"""Named-task registries.

Parity: the reference's task-name registries in
``polyaxon/polyaxon/config_settings/celery_settings.py`` —
``SchedulerCeleryTasks`` (:245), ``HPCeleryTasks`` (:304),
``PipelinesCeleryTasks`` (:179), ``CronsCeleryTasks`` (:141).  The celery
queue/routing machinery collapses away: one in-process bus, names kept for
the same reason the reference keeps them — the executor wires events to
task names, not functions.
"""


class SchedulerTasks:
    EXPERIMENTS_BUILD = "experiments.build"
    EXPERIMENTS_START = "experiments.start"
    EXPERIMENTS_MONITOR = "experiments.monitor"
    EXPERIMENTS_STOP = "experiments.stop"
    EXPERIMENTS_CHECK_HEARTBEAT = "experiments.check_heartbeat"
    ADMISSION_CHECK = "experiments.admission_check"
    ARTIFACTS_SYNC = "experiments.artifacts_sync"
    GROUPS_CREATE = "groups.create"
    GROUPS_STOP = "groups.stop"
    GROUPS_CHECK_DONE = "groups.check_done"


class HPTasks:
    CREATE = "hp.create"
    START = "hp.start"
    ITERATE = "hp.iterate"


class PipelineTasks:
    START = "pipelines.start"
    CHECK = "pipelines.check"
    STOP = "pipelines.stop"
    OPS_START = "pipelines.ops_start"


class CronTasks:
    HEARTBEAT_CHECK = "crons.heartbeat_check"
    STATUS_RECONCILE = "crons.status_reconcile"
    CLEAN_ACTIVITY = "crons.clean_activity"
    CLEAN_ARCHIVES = "crons.clean_archives"
