"""In-process task bus: the celery replacement.

Capability parity with the reference's async-orchestration layer
(``polyaxon/workers/__init__.py:10-14`` ``send(task_name, kwargs,
countdown)``, custom base task with retry, beat crons in
``celery_settings.py:740-860``).  The entire broker/queue/routing stack
collapses into one process: a priority queue ordered by due time, drained
either by a background thread (service mode) or by an explicit ``pump()``
(eager mode — what the reference's tests do with ``CELERY_TASK_ALWAYS_EAGER``,
``tests/base/case.py:79-87``).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class Retry(Exception):
    """Raised inside a task to requeue itself after ``countdown`` seconds."""

    def __init__(self, countdown: float = 1.0) -> None:
        super().__init__(f"retry in {countdown}s")
        self.countdown = countdown


class TaskBus:
    """Named tasks + delayed sends + crons, one process, thread-safe.

    ``time_scale`` multiplies every countdown/interval — tests compress the
    reference's 30 s scheduler waves (``celery_settings.py:71-74``) into
    milliseconds without changing orchestration code.
    """

    def __init__(
        self, *, time_scale: float = 1.0, max_retries: int = 100, stats=None
    ) -> None:
        self.time_scale = time_scale
        self.max_retries = max_retries
        #: Operational metrics sink (StatsBackend); None = no instrumentation.
        self.stats = stats
        self._tasks: Dict[str, Callable[..., Any]] = {}
        self._queue: List[Tuple[float, int, str, Dict[str, Any], int]] = []
        self._counter = itertools.count()
        self._lock = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._crons: List[Tuple[str, float, Dict[str, Any]]] = []
        #: Recent task failures (name, exception, traceback string) — a
        #: bounded window, NOT a full history: a cron failing every wave in
        #: a long-lived service would otherwise leak tracebacks forever.
        from collections import deque

        self.errors: "deque[Tuple[str, BaseException, str]]" = deque(maxlen=200)
        #: In-flight offloaded threads (service mode); stop() joins them.
        self._offloaded: List[threading.Thread] = []

    # -- registration ---------------------------------------------------------
    def register(self, name: str, fn: Optional[Callable[..., Any]] = None):
        """Register ``fn`` under ``name``; usable as a decorator."""
        if fn is None:
            def deco(f: Callable[..., Any]) -> Callable[..., Any]:
                self._tasks[name] = f
                return f
            return deco
        self._tasks[name] = fn
        return fn

    def has_task(self, name: str) -> bool:
        return name in self._tasks

    # -- sending --------------------------------------------------------------
    def send(
        self,
        name: str,
        kwargs: Optional[Dict[str, Any]] = None,
        countdown: float = 0.0,
        _retries: int = 0,
    ) -> None:
        if name not in self._tasks:
            raise KeyError(f"Unknown task {name!r}; registered: {sorted(self._tasks)}")
        due = time.monotonic() + countdown * self.time_scale
        with self._lock:
            heapq.heappush(self._queue, (due, next(self._counter), name, kwargs or {}, _retries))
            self._lock.notify_all()

    def add_cron(self, name: str, interval: float, kwargs: Optional[Dict[str, Any]] = None) -> None:
        """Beat-style recurring task (first fire after one interval).

        Idempotent per (name, kwargs): re-adding replaces the interval and
        does not seed a second chain (a stop/start cycle must not double the
        cron frequency).
        """
        kwargs = kwargs or {}
        for i, (n, _, k) in enumerate(self._crons):
            if n == name and k == kwargs:
                self._crons[i] = (name, interval, kwargs)
                return
        self._crons.append((name, interval, kwargs))
        self.send(name, kwargs, countdown=interval)

    # -- execution ------------------------------------------------------------
    def _run_one(self, name: str, kwargs: Dict[str, Any], retries: int) -> None:
        from polyaxon_tpu.tracking.trace import get_tracer

        fn = self._tasks[name]
        t0 = time.perf_counter()
        outcome = "ok"
        try:
            # Control-plane spans stay in the tracer's ring buffer (no
            # sink) — a cheap flight recorder of recent task executions.
            # The task name rides as an attribute, not in the span name:
            # interpolated names would mint one Perfetto track per task
            # (graft-lint GL008).
            with get_tracer().span("task.execute", task=name):
                fn(**kwargs)
        except Retry as r:
            outcome = "retry"
            if retries + 1 > self.max_retries:
                outcome = "dead_letter"
                logger.error("Task %s exhausted %d retries", name, self.max_retries)
                self.errors.append((name, r, f"max retries ({self.max_retries}) exhausted"))
                return
            self.send(name, kwargs, countdown=r.countdown, _retries=retries + 1)
        except Exception as e:  # noqa: BLE001 — a task must never kill the bus
            outcome = "error"
            logger.exception("Task %s failed", name)
            self.errors.append((name, e, traceback.format_exc()))
        finally:
            if self.stats is not None:
                # The celery-era task counters/timers (reference stats/):
                # throughput + latency per task name, failures by outcome.
                self.stats.incr(f"tasks.{name}.{outcome}")
                self.stats.timing(f"tasks.{name}", time.perf_counter() - t0)

    def _reschedule_cron(self, name: str, kwargs: Dict[str, Any]) -> None:
        for cron_name, interval, cron_kwargs in self._crons:
            if cron_name == name and cron_kwargs == kwargs:
                self.send(name, kwargs, countdown=interval)
                return

    def _is_cron(self, name: str, kwargs: Dict[str, Any]) -> bool:
        return any(n == name and k == kwargs for n, _, k in self._crons)

    def pump(self, *, max_wait: float = 0.0, max_tasks: Optional[int] = None) -> int:
        """Eagerly drain due tasks in the calling thread.

        Processes everything due now; if the queue holds only future tasks
        within ``max_wait`` seconds, sleeps until they come due and continues.
        Returns the number of tasks executed.  Crons are *not* rescheduled by
        pump (tests fire them explicitly; service mode reschedules).
        """
        deadline = time.monotonic() + max_wait
        executed = 0
        while max_tasks is None or executed < max_tasks:
            with self._lock:
                if not self._queue:
                    break
                due, _, name, kwargs, retries = self._queue[0]
                now = time.monotonic()
                if due > now:
                    if due > deadline:
                        break
                    wait = due - now
                else:
                    heapq.heappop(self._queue)
                    wait = None
            if wait is not None:
                time.sleep(wait)
                continue
            self._run_one(name, kwargs, retries)
            executed += 1
        return executed

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- heavy-task offload ----------------------------------------------------
    def offload(self, fn: Callable[[], Any], *, name: str = "offload") -> None:
        """Run ``fn`` without head-of-line-blocking the bus.

        On the service thread, ``fn`` moves to a worker thread so long IO
        (multi-GB artifact uploads) can't starve gang monitors, heartbeat
        checks, or stop requests queued behind it.  Anywhere else (eager
        ``pump()`` in tests, direct calls) it runs inline, keeping the
        task graph synchronous and deterministic.  ``fn`` must do its own
        failure handling — typically by re-sending its task with a bounded
        attempt counter — because a Retry raised on a worker thread has no
        bus frame to catch it.
        """
        if self._thread is not None and threading.current_thread() is self._thread:
            def _guarded() -> None:
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 — mirror _run_one
                    logger.exception("Offloaded %s failed", name)
                    self.errors.append((name, e, traceback.format_exc()))

            t = threading.Thread(target=_guarded, name=f"bus-{name}", daemon=True)
            with self._lock:
                self._offloaded = [x for x in self._offloaded if x.is_alive()]
                self._offloaded.append(t)
            t.start()
        else:
            fn()

    def offload_with_retry(
        self,
        fn: Callable[[], Any],
        *,
        task: str,
        kwargs: Dict[str, Any],
        attempt: int,
        max_attempts: int,
        countdown: float = 5.0,
        name: Optional[str] = None,
    ) -> None:
        """Offload ``fn`` with the bus's own retry/dead-letter accounting.

        The off-thread analogue of raising :class:`Retry` from a task: any
        exception re-sends ``task`` with ``kwargs + {"_attempt": n+1}``
        until ``max_attempts``, then dead-letters into the same stats
        counters and error window ``_run_one`` feeds — so heavy-IO tasks
        (artifact uploads) keep ONE retry implementation instead of each
        mirroring the bus's internals.
        """

        def guarded() -> None:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — retried, not fatal
                if attempt + 1 > max_attempts:
                    logger.exception(
                        "Offloaded task %s dead-lettered after %d attempts",
                        task,
                        attempt + 1,
                    )
                    if self.stats is not None:
                        self.stats.incr(f"tasks.{task}.dead_letter")
                    self.errors.append(
                        (
                            task,
                            e,
                            f"offloaded {task} dead-lettered after "
                            f"{attempt + 1} attempts\n{traceback.format_exc()}",
                        )
                    )
                    return
                logger.exception(
                    "Offloaded task %s failed (attempt %d)", task, attempt + 1
                )
                if self.stats is not None:
                    self.stats.incr(f"tasks.{task}.retry")
                self.send(
                    task, {**kwargs, "_attempt": attempt + 1}, countdown=countdown
                )

        self.offload(guarded, name=name or task)

    # -- service mode ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopping = False
        self._thread = threading.Thread(target=self._loop, name="taskbus", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._stopping = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        with self._lock:
            offloaded, self._offloaded = self._offloaded, []
        # One shared deadline across every in-flight offload — N stuck
        # uploads must not turn shutdown into N * timeout.
        deadline = time.monotonic() + timeout
        for t in offloaded:
            t.join(max(0.0, deadline - time.monotonic()))

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                if not self._queue:
                    self._lock.wait(timeout=0.5)
                    continue
                due, _, name, kwargs, retries = self._queue[0]
                now = time.monotonic()
                if due > now:
                    self._lock.wait(timeout=min(due - now, 0.5))
                    continue
                heapq.heappop(self._queue)
            self._run_one(name, kwargs, retries)
            if self._is_cron(name, kwargs):
                self._reschedule_cron(name, kwargs)
