from polyaxon_tpu.workers.bus import Retry, TaskBus
from polyaxon_tpu.workers.names import CronTasks, HPTasks, PipelineTasks, SchedulerTasks

__all__ = ["TaskBus", "Retry", "SchedulerTasks", "HPTasks", "PipelineTasks", "CronTasks"]
