"""Control-plane saturation loadgen: the flight-instrument bench harness.

Everything else in ``monitor/`` watches *workloads*; this module watches
the *watcher*.  It builds a real control plane (registry + watcher +
alert engine + aiohttp API — the same objects ``serve`` wires up, minus
the task bus) and then leans on it the way a busy deployment would:

- a registry pre-populated with ~1000 historical runs, so every list
  query and retention-facing read pays realistic row counts;
- N concurrent fake gangs whose writer threads append progress /
  heartbeat / metric report lines at a configured rate — the watcher
  must tail every file through its bounded-read ingest path;
- a monitor thread driving ``watcher.observe`` + ``alerts.evaluate``
  over every gang at a monitor-tick cadence, exactly like the scheduler
  monitor task;
- an API hammer issuing concurrent reads (run list, run detail,
  statuses, alerts, /metrics) against the in-process aiohttp app.

Mid-flight one gang's progress lines stop while its heartbeats continue —
the alive-but-stuck shape — and the harness times how long the
stall→alert pipeline takes beyond the configured ``stall_after_s``
threshold.  The three numbers the ``controlplane_saturation`` bench
section gates on come straight out of this run:

- ``watcher_ingest_lag_p99_s``: p99 of the fleet ingest-lag histogram
  the watcher itself exports (now − newest ingested line's own wall
  time) — the single best "is the control plane keeping up" signal;
- ``alert_fire_latency_s``: wall time from the earliest moment the
  stall *could* fire to the ``run_stalled`` FIRING transition;
- ``api_p99_s``: client-side p99 over all hammer requests, measured
  while ingest and monitoring run concurrently.

No part of this module is imported by the control plane proper; it is a
bench/test harness with zero production dependencies beyond the package
itself.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from polyaxon_tpu.compiler import GangPlan
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.spawner.local import GangHandle

#: Minimal experiment spec — enough for ``create_run`` and the API's
#: run serializers; the loadgen never dispatches it.
SPEC: Dict[str, Any] = {
    "kind": "experiment",
    "run": {"entrypoint": "noop:main"},
    "environment": {"topology": {"accelerator": "cpu", "num_devices": 2}},
}


class _IdleRef:
    """ProcessRef stand-in that is forever alive (poll → None)."""

    pid = 0

    def poll(self) -> Optional[int]:
        return None

    def signal(self, sig: int) -> None:  # pragma: no cover - never signalled
        pass


def populate(registry: Any, n_runs: int) -> int:
    """Bulk-create ``n_runs`` historical runs so every registry read and
    list query pays realistic row volume.  Returns the count created."""
    for i in range(n_runs):
        registry.create_run(dict(SPEC), name=f"hist-{i}", project="loadgen")
    return n_runs


def make_gang(orch: Any, *, num_procs: int = 2, name: str = "gang") -> GangHandle:
    """One live fake gang: a real run row, RUNNING process rows (so
    ``reconcile`` rolls up RUNNING), real report files under the store
    layout, and a real ``GangHandle`` whose members never exit."""
    run = orch.registry.create_run(dict(SPEC), name=name, project="loadgen")
    paths = orch.layout.run_paths(run.uuid).ensure()
    plan = GangPlan(
        num_hosts=num_procs,
        devices_per_host=1,
        mesh_axes={"data": num_procs},
        strategy="data_parallel",
    )
    handle = GangHandle(
        run_id=run.id,
        run_uuid=run.uuid,
        plan=plan,
        paths=paths,
        processes={pid: _IdleRef() for pid in range(num_procs)},
    )
    for pid in range(num_procs):
        orch.registry.upsert_process(
            run.id, pid, pid=10_000 + pid, status=S.RUNNING
        )
    return handle


class _GangWriter(threading.Thread):
    """Appends report lines for every process of one gang at ``write_hz``.

    Clearing ``progress_on`` simulates the alive-but-stuck failure shape:
    heartbeats and metrics keep flowing (liveness stays fresh) while
    forward progress stops — exactly what the stall detector keys on.
    """

    def __init__(self, handle: GangHandle, *, write_hz: float, stop: threading.Event) -> None:
        super().__init__(daemon=True, name=f"loadgen-writer-{handle.run_id}")
        self.handle = handle
        self.interval = 1.0 / max(write_hz, 0.1)
        self.stop_event = stop
        self.progress_on = threading.Event()
        self.progress_on.set()
        #: Wall time of the last progress line written (stall T0 anchor).
        self.last_progress_at = 0.0
        self.step = 0

    def run(self) -> None:
        files = {
            pid: open(self.handle.paths.report_file(pid), "a", encoding="utf-8")
            for pid in range(self.handle.plan.num_hosts)
        }
        try:
            while not self.stop_event.is_set():
                now = time.time()
                self.step += 1
                for pid, fh in files.items():
                    lines = [
                        {"type": "heartbeat", "ts": now},
                        {
                            "type": "metric",
                            "ts": now,
                            "step": self.step,
                            "values": {"loss": 1.0 / self.step},
                        },
                    ]
                    if self.progress_on.is_set():
                        lines.append(
                            {
                                "type": "progress",
                                "step": self.step,
                                "at": now,
                                "ts": now,
                                "throughput": 100.0,
                            }
                        )
                        self.last_progress_at = now
                    for line in lines:
                        fh.write(json.dumps(line) + "\n")
                    fh.flush()
                self.stop_event.wait(self.interval)
        finally:
            for fh in files.values():
                fh.close()


class _MonitorLoop(threading.Thread):
    """The scheduler monitor task, reduced to its watcher+alerts core:
    one ``observe`` + ``evaluate`` pass per gang per tick.  Records the
    wall time of the first ``run_stalled`` FIRING transition."""

    def __init__(
        self,
        orch: Any,
        handles: List[GangHandle],
        *,
        interval_s: float,
        stop: threading.Event,
    ) -> None:
        super().__init__(daemon=True, name="loadgen-monitor")
        self.orch = orch
        self.handles = handles
        self.interval_s = interval_s
        self.stop_event = stop
        self.stall_fired_at: Optional[float] = None
        self.ticks = 0
        self.errors = 0

    def run(self) -> None:
        while not self.stop_event.is_set():
            self.ticks += 1
            for handle in self.handles:
                try:
                    self.orch.watcher.observe(handle)
                    transitions = self.orch.alerts.evaluate(handle)
                except Exception:
                    self.errors += 1
                    continue
                if self.stall_fired_at is None:
                    for row in transitions:
                        if (
                            row.get("rule") == "run_stalled"
                            and row.get("state") == "firing"
                        ):
                            self.stall_fired_at = time.time()
            self.stop_event.wait(self.interval_s)


async def _hammer_api(
    app: Any,
    paths: List[str],
    *,
    duration_s: float,
    concurrency: int,
    done: threading.Event,
) -> Dict[str, Any]:
    """Concurrent read hammer against the in-process aiohttp app; returns
    client-side latency samples.  Stops at ``duration_s`` or when the
    driver sets ``done`` (whichever is first)."""
    from aiohttp.test_utils import TestClient, TestServer

    latencies: List[float] = []
    errors = [0]
    server = TestServer(app)
    client = TestClient(server)
    await client.start_server()
    try:
        deadline = time.perf_counter() + duration_s

        async def worker(offset: int) -> None:
            i = offset
            while time.perf_counter() < deadline and not done.is_set():
                path = paths[i % len(paths)]
                i += 1
                t0 = time.perf_counter()
                try:
                    async with client.get(path) as resp:
                        await resp.read()
                        if resp.status >= 500:
                            errors[0] += 1
                except Exception:
                    errors[0] += 1
                latencies.append(time.perf_counter() - t0)

        await asyncio.gather(*(worker(i) for i in range(concurrency)))
    finally:
        await client.close()
    return {"latencies": latencies, "errors": errors[0]}


def _p99(samples: List[float]) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(0.99 * (len(ordered) - 1)))]


def run_saturation(
    base_dir: Union[str, Path],
    *,
    n_registry_runs: int = 1000,
    n_gangs: int = 8,
    procs_per_gang: int = 2,
    duration_s: float = 6.0,
    write_hz: float = 20.0,
    api_concurrency: int = 4,
    stall_after_s: float = 0.75,
    monitor_interval_s: float = 0.05,
) -> Dict[str, Any]:
    """One full saturation episode; returns the bench metrics dict.

    The ``run_stalled`` rule reads its threshold through the env knob
    (``RuleContext.anomaly`` resolves knobs, not watcher ctor state), so
    the stall window is installed via environment for the duration of
    the run and restored after.
    """
    from polyaxon_tpu.api.app import API_PREFIX, create_app
    from polyaxon_tpu.orchestrator import Orchestrator

    knobs = {"POLYAXON_TPU_STALL_AFTER_S": str(stall_after_s)}
    saved_env = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    stop = threading.Event()
    writers: List[_GangWriter] = []
    monitor: Optional[_MonitorLoop] = None
    try:
        orch = Orchestrator(base_dir, monitor_interval=monitor_interval_s)
        populate(orch.registry, n_registry_runs)
        # Alert cadence: evaluate every monitor pass — the throttle is the
        # thing under test, not a variable.
        orch.alerts.interval_s = 0.0
        orch.watcher.stall_after_s = stall_after_s

        handles = [
            make_gang(orch, num_procs=procs_per_gang, name=f"gang-{i}")
            for i in range(n_gangs)
        ]
        for handle in handles:
            writers.append(_GangWriter(handle, write_hz=write_hz, stop=stop))
        monitor = _MonitorLoop(
            orch, handles, interval_s=monitor_interval_s, stop=stop
        )
        for w in writers:
            w.start()
        monitor.start()

        stalled = writers[0]
        stall_at = time.perf_counter() + duration_s * 0.35

        async def drive() -> Dict[str, Any]:
            app = create_app(orch)
            rid = handles[-1].run_id
            paths = [
                f"{API_PREFIX}/runs?limit=50",
                f"{API_PREFIX}/runs/{rid}",
                f"{API_PREFIX}/runs/{rid}/statuses",
                f"{API_PREFIX}/alerts",
                "/metrics",
            ]
            hammer = asyncio.create_task(
                _hammer_api(
                    app,
                    paths,
                    duration_s=duration_s,
                    concurrency=api_concurrency,
                    done=stop,
                )
            )
            # Mid-flight stall injection: progress stops, heartbeats
            # continue — the alert must fire while the hammer still runs.
            await asyncio.sleep(max(0.0, stall_at - time.perf_counter()))
            stalled.progress_on.clear()
            return await hammer

        api_out = asyncio.run(drive())
        progress_stopped_at = stalled.last_progress_at or time.time()
        # Give the monitor loop a short grace window past the hammer to
        # catch a fire that lands right at the deadline.
        fire_deadline = time.time() + max(2.0, stall_after_s * 2)
        while monitor.stall_fired_at is None and time.time() < fire_deadline:
            time.sleep(monitor_interval_s)
    finally:
        stop.set()
        for w in writers:
            w.join(timeout=5)
        if monitor is not None:
            monitor.join(timeout=5)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    lag_summary = {}
    try:
        lag_summary = orch.stats.summaries().get("watcher_ingest_lag_s", {})
    except Exception:
        pass
    alert_fire_latency = None
    if monitor.stall_fired_at is not None:
        # Earliest possible fire = last progress beat + stall threshold;
        # anything beyond that is control-plane detection latency.
        alert_fire_latency = max(
            0.0, monitor.stall_fired_at - (progress_stopped_at + stall_after_s)
        )
    return {
        "n_registry_runs": n_registry_runs,
        "n_gangs": n_gangs,
        "procs_per_gang": procs_per_gang,
        "duration_s": duration_s,
        "write_hz": write_hz,
        "monitor_ticks": monitor.ticks,
        "monitor_errors": monitor.errors,
        "report_bytes_ingested": sum(
            sum(h.report_offsets.values()) for h in handles
        ),
        "watcher_ingest_lag_p99_s": (
            round(lag_summary["p99"], 4) if "p99" in lag_summary else None
        ),
        "watcher_ingest_lag_samples": int(lag_summary.get("count", 0)),
        "alert_fire_latency_s": (
            round(alert_fire_latency, 3)
            if alert_fire_latency is not None
            else None
        ),
        "api_requests": len(api_out["latencies"]),
        "api_errors": api_out["errors"],
        "api_p99_s": (
            round(_p99(api_out["latencies"]), 4)
            if api_out["latencies"]
            else None
        ),
    }


class _StubRouter:
    """In-process stand-in for ``serving/router.py``: advances its
    counters on every ``stats()`` read so the scraper's windowed deltas
    see monotonically growing traffic, and serves a full ``/v1/stats``
    field set per replica so the per-replica series fan-out is paid at
    realistic width."""

    def __init__(self, n_replicas: int) -> None:
        self.n_replicas = n_replicas
        self._requests = 0
        self._sheds = 0

    def stats(self) -> Dict[str, Any]:
        self._requests += 37
        self._sheds += 2
        return {
            "n_ready": self.n_replicas,
            "counters": {
                "requests": self._requests,
                "sheds": self._sheds,
                "retries": 0,
                "failovers": 0,
                "ejections": 0,
                "readmissions": 0,
                "drains": 0,
                "upstream_errors": 0,
            },
        }

    def replica_stats(self) -> Dict[str, Dict[str, Any]]:
        return {
            f"r{i}": {
                "slots": 8,
                "slots_active": i % 8,
                "queue_depth": i % 4,
                "blocks_free": 1000 - i,
                "block_occupancy": 0.5,
                "prefix_cache_hit_rate": 0.7,
                "prefix_cache_hit_rate_window": 0.65,
                "spec_accept_rate": 0.8,
                "spec_accept_rate_window": 0.75,
                "requests_submitted": self._requests,
                "requests_finished": max(0, self._requests - 1),
                "requests_shed": self._sheds,
                "tokens_generated": self._requests * 40,
                "tokens_per_s": 1200.0,
                "decode_steps": self._requests * 10,
            }
            for i in range(self.n_replicas)
        }


class _StubFleet:
    """Fleet stand-in the scraper sees through ``orch.fleets``."""

    def __init__(self, name: str, n_replicas: int) -> None:
        self.name = name
        self.router = _StubRouter(n_replicas)


def run_scrape_overhead(
    base_dir: Union[str, Path],
    *,
    n_registry_runs: int = 1000,
    n_replicas: int = 16,
    n_gangs: int = 4,
    duration_s: float = 4.0,
    monitor_interval_s: float = 0.05,
    api_duration_s: float = 2.0,
    api_concurrency: int = 2,
) -> Dict[str, Any]:
    """Measure the metric-history pipeline's two bench numbers:

    - ``scrape_share``: the scrape phase's fraction of the monitor
      tick's total work at the production cadence ratio — one full
      fleet scrape + registry flush per 25 ticks (default 5s scrape
      interval over the default 0.2s monitor interval), amortised over
      the whole run so throttled no-op passes count like they do in a
      real deployment;
    - ``query_p99_s``: client-side p99 of ``/api/v1/metrics/query`` and
      the per-run history read against the in-process aiohttp app, on a
      registry pre-populated with ``n_registry_runs`` historical runs.
    """
    from polyaxon_tpu.api.app import API_PREFIX, create_app
    from polyaxon_tpu.orchestrator import Orchestrator

    # Production fires one scrape per scrape_interval/monitor_interval
    # ticks (5s / 0.2s = 25); the bench compresses both intervals by the
    # same factor so the amortised phase share is cadence-faithful.
    scrape_every_ticks = 25
    knobs = {
        "POLYAXON_TPU_TSDB_ENABLED": "1",
        "POLYAXON_TPU_TSDB_SCRAPE_INTERVAL_S": str(
            monitor_interval_s * scrape_every_ticks
        ),
    }
    saved_env = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    stop = threading.Event()
    writers: List[_GangWriter] = []
    try:
        orch = Orchestrator(base_dir, monitor_interval=monitor_interval_s)
        populate(orch.registry, n_registry_runs)
        orch.alerts.interval_s = 0.0
        orch.fleets.append(_StubFleet("bench", n_replicas))
        handles = [
            make_gang(orch, num_procs=2, name=f"gang-scrape-{i}")
            for i in range(n_gangs)
        ]
        writers.extend(
            _GangWriter(h, write_hz=20.0, stop=stop) for h in handles
        )
        for w in writers:
            w.start()

        # Warm pass: first scrape allocates every series ring + the key
        # cache and first observe creates cursors — steady state is what
        # the phase-share gate is about.
        orch.scraper.tick(time.time())
        for handle in handles:
            orch.watcher.observe(handle)
            orch.alerts.evaluate(handle)

        # The scheduler fans the monitor tick out per gang but the
        # scraper throttles itself, so one pass here = one scrape check
        # plus a full watcher+alerts sweep — the same per-tick work mix.
        scrape_s = 0.0
        base_s = 0.0
        ticks = 0
        deadline = time.perf_counter() + duration_s
        while time.perf_counter() < deadline:
            ticks += 1
            t0 = time.perf_counter()
            orch.scraper.tick(time.time())
            t1 = time.perf_counter()
            for handle in handles:
                orch.watcher.observe(handle)
                orch.alerts.evaluate(handle)
            t2 = time.perf_counter()
            scrape_s += t1 - t0
            base_s += t2 - t1
            time.sleep(monitor_interval_s)

        rid = handles[0].run_id

        async def drive() -> Dict[str, Any]:
            app = create_app(orch)
            paths = [
                f"{API_PREFIX}/metrics/query?series=replica_slots_active"
                "&fleet=bench&step=1",
                f"{API_PREFIX}/metrics/query?series=router_requests_total"
                "&fleet=bench",
                f"{API_PREFIX}/runs/{rid}/metrics/history?limit=200",
            ]
            return await _hammer_api(
                app,
                paths,
                duration_s=api_duration_s,
                concurrency=api_concurrency,
                done=stop,
            )

        api_out = asyncio.run(drive())
    finally:
        stop.set()
        for w in writers:
            w.join(timeout=5)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    store_status = orch.metrics.status() if orch.metrics is not None else {}
    scraper_status = orch.scraper.status() if orch.scraper is not None else {}
    total = scrape_s + base_s
    return {
        "n_registry_runs": n_registry_runs,
        "n_replicas": n_replicas,
        "ticks": ticks,
        "scrape_s_total": round(scrape_s, 4),
        "tick_s_total": round(total, 4),
        "scrape_share": round(scrape_s / total, 4) if total > 0 else None,
        "series": store_status.get("series"),
        "dropped_samples": store_status.get("dropped"),
        "flushed_rows": scraper_status.get("flushed_rows"),
        "scrape_errors": scraper_status.get("errors"),
        "query_requests": len(api_out["latencies"]),
        "query_errors": api_out["errors"],
        "query_p99_s": (
            round(_p99(api_out["latencies"]), 4)
            if api_out["latencies"]
            else None
        ),
    }


def measure_idle_tick_us(base_dir: Union[str, Path], *, iters: int = 200) -> float:
    """Instrumentation overhead floor: µs per watcher+alerts pass over one
    idle gang (no new report lines, nothing pending).  This is the cost
    every deployment pays per monitor tick whether or not anything is
    happening — the number the bench holds to the ``alert_tick_us``-style
    budget."""
    from polyaxon_tpu.orchestrator import Orchestrator

    orch = Orchestrator(base_dir)
    orch.alerts.interval_s = 0.0
    handle = make_gang(orch, num_procs=1, name="idle")
    # Warm the path (first observe creates cursors/rows).
    orch.watcher.observe(handle)
    orch.alerts.evaluate(handle)
    t0 = time.perf_counter()
    for _ in range(iters):
        orch.watcher.observe(handle)
        orch.alerts.evaluate(handle)
    return (time.perf_counter() - t0) / iters * 1e6
