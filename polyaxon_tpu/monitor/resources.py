"""In-worker resource telemetry sampler.

Parity: reference ``monitor_resources/`` — the per-node DaemonSet reading
docker stats + ``polyaxon_gpustat.query()`` (NVML) and publishing to Redis
for the streams layer (``monitor_resources/monitor.py:30-120``).
TPU-native: each gang process samples itself (psutil process stats) and its
local accelerator (``device.memory_stats()`` from the PJRT client — the
libtpu telemetry path), reporting through the same reports channel as
metrics; rows land in the registry prefixed ``sys/`` so the WS metric tail
streams them live.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


# psutil computes cpu_percent(interval=None) against the PREVIOUS call on
# the same Process object — a fresh object's first call always returns
# 0.0.  Keep one Process per sampled pid so every call after the first
# measures a real interval; the priming call reports no cpu row at all
# instead of a fabricated zero.
_proc_cache: Dict[int, Any] = {}
_proc_cache_lock = threading.Lock()


def sample_process(pid: Optional[int] = None) -> Dict[str, float]:
    """CPU / memory of the given (default: calling) process."""
    out: Dict[str, float] = {}
    key = -1 if pid is None else pid
    try:
        import psutil

        with _proc_cache_lock:
            p = _proc_cache.get(key)
            primed = p is not None
            if p is None:
                p = psutil.Process(pid)
                _proc_cache[key] = p
        try:
            with p.oneshot():
                cpu = p.cpu_percent(interval=None)
                if primed:
                    out["sys/cpu_percent"] = cpu
                out["sys/rss_mb"] = p.memory_info().rss / 1e6
                out["sys/threads"] = float(p.num_threads())
        except Exception:
            # Target gone (or pid reused): drop the cached handle so a
            # later process with the same pid re-primes cleanly.
            with _proc_cache_lock:
                _proc_cache.pop(key, None)
            raise
    except Exception:
        if pid is not None:
            return out  # target process gone; report nothing rather than self
        try:
            import resource

            out["sys/rss_mb"] = (
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e3
            )
        except Exception:
            pass
    return out


# Probe-once gate for the accelerator sampler: CPU and older PJRT backends
# have no memory_stats() — the first sample that yields no memory telemetry
# disables the device sampler for the process lifetime instead of paying a
# device walk (and swallowing an exception) on every tick.
_device_probe_ok: Optional[bool] = None
_device_probe_lock = threading.Lock()
_hbm_peak_mb = 0.0


def _reset_device_probe() -> None:
    """Re-arm the probe (tests; a process never needs this)."""
    global _device_probe_ok, _hbm_peak_mb
    with _device_probe_lock:
        _device_probe_ok = None
        _hbm_peak_mb = 0.0


def sample_devices() -> Dict[str, float]:
    """Per-local-device HBM usage from the PJRT client, if initialized.

    Degrades gracefully: the first sample without memory telemetry turns
    the sampler off (``_device_probe_ok = False``) rather than raising —
    or even probing — on every tick.  Emits per-device current and peak
    usage plus an aggregate ``sys/hbm_peak_mb`` high-water mark.
    """
    global _device_probe_ok, _hbm_peak_mb
    out: Dict[str, float] = {}
    import sys

    if "jax" not in sys.modules:
        # No jax in this process yet → no PJRT client to sample, and the
        # telemetry thread must not be the thing that pays the jax import
        # (non-jax gang workloads boot ~2s faster without it).  Leaves the
        # probe unanswered: jax may still be imported later.
        return out
    with _device_probe_lock:
        if _device_probe_ok is False:
            return out
    total_peak_mb = 0.0
    got_any = False
    try:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            peak = stats.get("peak_bytes_in_use")
            if in_use is not None:
                got_any = True
                out[f"sys/hbm{d.id}_mb"] = in_use / 1e6
            if in_use is not None and limit:
                out[f"sys/hbm{d.id}_frac"] = in_use / limit
            if peak is not None:
                out[f"sys/hbm{d.id}_peak_mb"] = peak / 1e6
                total_peak_mb += peak / 1e6
            elif in_use is not None:
                total_peak_mb += in_use / 1e6
    except Exception:
        pass
    with _device_probe_lock:
        if _device_probe_ok is None:
            _device_probe_ok = got_any
        if got_any:
            _hbm_peak_mb = max(_hbm_peak_mb, total_peak_mb)
            out["sys/hbm_peak_mb"] = _hbm_peak_mb
    return out


def sample_tpu_utilization() -> Dict[str, float]:
    """TensorCore duty cycle per chip via the ``tpu_info`` library (the
    gpustat analogue — reference ``monitor_resources/monitor.py:30-34``
    polled NVML; on TPU-VMs the equivalent is libtpu's metrics endpoint,
    which ``tpu-info`` wraps).  Gated: returns {} wherever the library or
    the endpoint is absent (CPU test boxes, tunneled single-chip dev), so
    the sampler composes it unconditionally."""
    out: Dict[str, float] = {}
    try:
        from tpu_info import device as tpu_device
        from tpu_info import metrics as tpu_metrics

        chip_type, count = tpu_device.get_local_chips()
        if not chip_type or not count:
            return out
        for i, usage in enumerate(tpu_metrics.get_chip_usage(chip_type)):
            duty = getattr(usage, "duty_cycle_pct", None)
            if duty is not None:
                out[f"sys/tpu{i}_duty_pct"] = float(duty)
            used = getattr(usage, "memory_usage", None)
            total = getattr(usage, "total_memory", None)
            if used is not None:
                out[f"sys/tpu{i}_mem_mb"] = float(used) / 1e6
            if used is not None and total:
                out[f"sys/tpu{i}_mem_frac"] = float(used) / float(total)
    except Exception:
        pass
    return out


class ResourceSampler:
    """Background thread reporting resource samples at an interval."""

    def __init__(self, reporter, interval: float = 10.0) -> None:
        self.reporter = reporter
        self.interval = interval
        #: When set, sample this pid instead of the calling process — the
        #: shell-command path points this at the user's subprocess, so
        #: telemetry reflects the workload, not the idle wrapper.
        self.pid: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> Dict[str, Any]:
        values = sample_process(self.pid)
        values.update(sample_devices())
        values.update(sample_tpu_utilization())
        return values

    def start(self) -> None:
        if self._thread is not None or self.interval <= 0:
            return
        # Prime the per-process cpu_percent window now (unreported), so
        # the first row the loop emits measures a real interval.
        sample_process(self.pid)

        def loop() -> None:
            while not self._stop.wait(self.interval):
                values = self.sample_once()
                if values:
                    self.reporter.resources(values)

        self._thread = threading.Thread(target=loop, name="resources", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
