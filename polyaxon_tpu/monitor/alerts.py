"""Alert & SLO engine: continuous rule evaluation over platform signals.

Alertmanager-shaped, registry-backed.  The control-plane scheduler ticks
:class:`AlertEngine` alongside the ``GangWatcher`` (same monitor task, same
cadence); each tick evaluates a catalog of :class:`AlertRule` predicates
over what the registry and stats layer already hold — stall/straggler
roll-ups (``anomaly_status``), goodput/MFU ratios (``goodput_status``),
heartbeat staleness, serving latency histogram quantiles, steady-state
recompiles, compile-cache miss ratios — and drives each (run, rule) pair
through a **pending → firing → resolved** lifecycle:

- a violated predicate enters PENDING and must stay violated for the
  rule's ``for_s`` hold-down before it FIRES (flap suppression: a pending
  alert that recovers inside the hold-down vanishes without a trace);
- FIRING and RESOLVED are *edges*, exactly like the PR 4 anomaly
  detector: each routes one notification through the auditor
  (``alert.firing`` / ``alert.resolved`` events → ``AlertRouter`` →
  webhook/email/log sinks) and re-inserts the registry ``alerts`` row so
  since_id pagers and the WS tail observe the transition;
- gauges (``alert_state{rule,run,severity}``: 0 ok / 1 pending /
  2 firing) recover to 0 on resolve and on a run going terminal
  mid-episode — the same discipline as ``run_stall_age_s``.

Rule parameters resolve per evaluation:
run declarations (``alert.<rule>.<param>``) → env knob
(``POLYAXON_TPU_ALERT_<RULE>_<PARAM>``) → rule default.  Rule evaluation
errors are counted (``alert_eval_errors``), never raised — a broken rule
must not take the monitor loop down with it.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from polyaxon_tpu.conf.knobs import family_float, family_value, knob_float
from polyaxon_tpu.db.registry import (
    AlertSeverity,
    AlertState,
    Run,
    RunRegistry,
)
from polyaxon_tpu.events import EventTypes
from polyaxon_tpu.monitor.watcher import anomaly_status, goodput_status
from polyaxon_tpu.stats.metrics import labeled_key
from polyaxon_tpu.stats.tsdb import slo_status

logger = logging.getLogger(__name__)

__all__ = [
    "AlertEngine",
    "AlertRule",
    "RuleContext",
    "default_rules",
    "alert_gauge_key",
    "run_slo_status",
]


def alert_gauge_key(rule: str, run_id: int, severity: str) -> str:
    return labeled_key(
        "alert_state", rule=rule, run=str(run_id), severity=severity
    )


#: Gauge values per lifecycle state (``alert_state`` exposition).
GAUGE_OK = 0.0
GAUGE_PENDING = 1.0
GAUGE_FIRING = 2.0


class RuleContext:
    """One tick's evaluation inputs for one run.

    Registry roll-ups (``anomaly_status`` / ``goodput_status`` / stats
    snapshot) are computed lazily and cached for the tick, so a catalog of
    N rules costs one read per *signal*, not per rule.
    """

    def __init__(
        self,
        registry: RunRegistry,
        run: Run,
        *,
        stats: Any = None,
        metrics: Any = None,
        now: Optional[float] = None,
    ) -> None:
        self.registry = registry
        self.run = run
        self.stats = stats
        #: Metric history (``stats.tsdb.MetricStore``) — windowed rates
        #: for the burn-rate rules; None on stores without a scrape phase.
        self.metrics = metrics
        self.now = now if now is not None else time.time()
        self._anomaly: Optional[Dict[str, Any]] = None
        self._goodput: Optional[Dict[str, Any]] = None
        self._snapshot: Optional[Dict[str, Any]] = None
        self._overrides: Optional[Dict[str, Any]] = None

    # -- cached signal reads ---------------------------------------------------
    @property
    def anomaly(self) -> Dict[str, Any]:
        if self._anomaly is None:
            self._anomaly = anomaly_status(
                self.registry, self.run.id, now=self.now
            )
        return self._anomaly

    @property
    def goodput(self) -> Dict[str, Any]:
        if self._goodput is None:
            self._goodput = goodput_status(
                self.registry, self.run.id, timeline_limit=0
            )
        return self._goodput

    @property
    def snapshot(self) -> Dict[str, Any]:
        if self._snapshot is None:
            snap = getattr(self.stats, "snapshot", None)
            self._snapshot = snap() if callable(snap) else {}
        return self._snapshot

    def counter(self, key: str) -> float:
        return float(self.snapshot.get("counters", {}).get(key, 0) or 0)

    def histogram_quantile(self, key: str, q: float) -> Optional[float]:
        """Quantile estimate from the stats backend's histogram state, or
        None when the series has never been observed in this process."""
        state = self.snapshot.get("histograms", {}).get(key)
        if not state or not state.get("count"):
            return None
        # Re-walk the bucket counts (Histogram.quantile over a state dict).
        edges = state["edges"]
        counts = state["counts"]
        target = max(1.0, q * state["count"])
        running = 0
        for i, n in enumerate(counts):
            if n and running + n >= target:
                lo = edges[i - 1] if i > 0 else 0.0
                hi = edges[i] if i < len(edges) else edges[-1]
                return lo + (hi - lo) * ((target - running) / n)
            running += n
        return float(edges[-1])

    def dump_artifact(self, kind: str) -> Optional[str]:
        """Run-relative flight-recorder dump key from the newest anomaly
        row of ``kind``, so the alert payload links to the postmortem."""
        try:
            rows = self.registry.get_anomalies(self.run.id, kind=kind)
        except Exception:
            return None
        for row in reversed(rows):
            key = (row.get("attrs") or {}).get("dump_artifact")
            if key:
                return str(key)
        return None

    # -- parameter resolution --------------------------------------------------
    @property
    def overrides(self) -> Dict[str, Any]:
        """Per-run ``alert.*`` declarations, stripped of the prefix."""
        if self._overrides is None:
            decls = (self.run.spec_data or {}).get("declarations") or {}
            self._overrides = {
                k[len("alert."):]: v
                for k, v in decls.items()
                if isinstance(k, str) and k.startswith("alert.")
            }
        return self._overrides

    def param(self, rule: str, name: str, default: float) -> float:
        """``alert.<rule>.<name>`` declaration → env knob → default."""
        val = self.overrides.get(f"{rule}.{name}")
        if val is not None:
            try:
                return float(val)
            except (TypeError, ValueError):
                pass
        return family_float(
            "POLYAXON_TPU_ALERT_", f"{rule.upper()}_{name.upper()}", default
        )

    def param_str(self, rule: str, name: str, default: str) -> str:
        """String-valued rule parameter (series names, SLO labels) with
        the same resolution order as :meth:`param`."""
        val = self.overrides.get(f"{rule}.{name}")
        if val is not None:
            return str(val)
        val = family_value("POLYAXON_TPU_ALERT_", f"{rule.upper()}_{name.upper()}")
        return str(val) if val is not None else default

    def enabled(self, rule: str) -> bool:
        val = self.overrides.get(f"{rule}.enabled")
        if val is None:
            val = family_value("POLYAXON_TPU_ALERT_", f"{rule.upper()}_ENABLED")
        if val is None:
            return True
        return str(val).lower() not in ("0", "false", "no", "off")


@dataclass
class AlertRule:
    """One predicate in the catalog.

    ``check(ctx)`` returns None when healthy, or a violation dict —
    ``{"value": float, "message": str, ...attrs}`` — when the predicate
    holds.  ``for_s`` is the hold-down: how long the violation must
    persist before the alert fires (overridable per run/env like every
    other param, via ``param(rule, "for_s", ...)``).
    """

    name: str
    severity: str
    for_s: float
    check: Callable[[RuleContext], Optional[Dict[str, Any]]]
    description: str = ""


# -- built-in rule catalog ------------------------------------------------------


def _check_run_stalled(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    status = ctx.anomaly
    if not status["stalled"]:
        return None
    out: Dict[str, Any] = {
        "value": float(status["stall_age_s"]),
        "message": (
            f"gang alive but no progress for {status['stall_age_s']:.1f}s"
        ),
        "steps": [r["step"] for r in status["progress"]],
    }
    dump = ctx.dump_artifact("stall")
    if dump:
        out["dump_artifact"] = dump
    return out


def _check_gang_straggler(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    stragglers = ctx.anomaly["stragglers"]
    if not stragglers:
        return None
    worst = max(stragglers, key=lambda s: s["lag_steps"])
    return {
        "value": float(worst["lag_steps"]),
        "message": (
            f"proc {worst['process_id']} lags the gang median by "
            f"{worst['lag_steps']:.0f} steps"
        ),
        "stragglers": stragglers,
    }


def _check_heartbeat_stale(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    hb = ctx.registry.last_heartbeat(ctx.run.id)
    if hb is None:
        return None  # never phoned home — reconcile's problem, not an SLO's
    threshold = ctx.param("heartbeat_stale", "threshold_s", 120.0)
    age = ctx.now - hb
    if age <= threshold:
        return None
    return {
        "value": float(age),
        "message": f"last gang heartbeat {age:.1f}s ago (> {threshold:.0f}s)",
        "threshold_s": threshold,
    }


def _check_goodput_low(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    floor = ctx.param("goodput_low", "floor", 0.0)
    if floor <= 0:
        return None  # off until an SLO is declared
    gp = ctx.goodput
    min_wall = ctx.param("goodput_low", "min_wall_s", 60.0)
    if not gp["rows"] or gp["wall_s"] < min_wall:
        return None
    if gp["goodput_ratio"] >= floor:
        return None
    return {
        "value": float(gp["goodput_ratio"]),
        "message": (
            f"goodput {gp['goodput_ratio']:.3f} below SLO floor {floor:.3f}"
        ),
        "floor": floor,
    }


def _check_mfu_low(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    floor = ctx.param("mfu_low", "floor", 0.0)
    if floor <= 0:
        return None
    gp = ctx.goodput
    min_wall = ctx.param("mfu_low", "min_wall_s", 60.0)
    if not gp["rows"] or gp["wall_s"] < min_wall:
        return None
    if gp["mfu"] >= floor:
        return None
    return {
        "value": float(gp["mfu"]),
        "message": f"MFU {gp['mfu']:.3f} below SLO floor {floor:.3f}",
        "floor": floor,
    }


def _check_serving_ttft_p99(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    threshold = ctx.param("serving_ttft_p99", "threshold_s", 0.0)
    if threshold <= 0:
        return None
    p99 = ctx.histogram_quantile("serving.ttft_s", 0.99)
    if p99 is None or p99 <= threshold:
        return None
    out = {
        "value": float(p99),
        "message": f"serving TTFT p99 {p99:.3f}s above SLO {threshold:.3f}s",
        "threshold_s": threshold,
    }
    # Slow-request exemplars: the engine keeps fully-traced waterfalls
    # for the slowest requests of the window (see ServingEngine stats
    # "trace_exemplars"); the control plane lands them as a "ttft_slow"
    # anomaly whose dump_artifact points at the written exemplar file —
    # the alert carries WHICH requests blew the SLO, not just that p99
    # did.
    artifact = ctx.dump_artifact("ttft_slow")
    if artifact:
        out["exemplar_artifact"] = artifact
    return out


def _check_steady_state_compiles(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    compiles = ctx.counter("serving.steady_state_compiles")
    if compiles <= 0:
        return None
    return {
        "value": float(compiles),
        "message": (
            f"{compiles:.0f} recompilations after warmup — the "
            f"zero-recompile invariant is broken"
        ),
    }


def _check_compile_cache_miss(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    gp = ctx.goodput
    hits = gp["compile_cache_hits"]
    misses = gp["compile_cache_misses"]
    events = hits + misses
    min_events = ctx.param("compile_cache_miss", "min_events", 8.0)
    if events < min_events:
        return None
    ratio = misses / events
    threshold = ctx.param("compile_cache_miss", "ratio", 0.5)
    if ratio <= threshold:
        return None
    return {
        "value": float(ratio),
        "message": (
            f"compile cache miss ratio {ratio:.2f} "
            f"({misses}/{events} events) above {threshold:.2f}"
        ),
        "hits": hits,
        "misses": misses,
    }


def run_slo_status(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    """Resolved burn-rate SLO status for one run, or None when no error
    budget is declared (``alert.slo_burn_rate.target``), the metric
    store is absent, or the total series has no history yet.  Shared by
    the ``slo_burn_rate`` rule and the run-detail API's ``slo`` block —
    one implementation of the budget math, two consumers."""
    target = ctx.param("slo_burn_rate", "target", 0.0)
    if target <= 0 or ctx.metrics is None:
        return None
    name = ctx.param_str("slo_burn_rate", "name", "shed")
    bad = ctx.param_str("slo_burn_rate", "bad_series", "router_sheds_total")
    total = ctx.param_str(
        "slo_burn_rate", "total_series", "router_requests_total"
    )
    status = slo_status(
        ctx.metrics,
        bad=bad,
        total=total,
        target=target,
        fast_s=ctx.param("slo_burn_rate", "fast_window_s", 60.0),
        slow_s=ctx.param("slo_burn_rate", "slow_window_s", 300.0),
        now=ctx.now,
    )
    if status is None:
        return None
    status["name"] = name
    status["bad_series"] = bad
    status["total_series"] = total
    status["burn_threshold"] = ctx.param(
        "slo_burn_rate", "burn_threshold", 2.0
    )
    status["min_total"] = ctx.param("slo_burn_rate", "min_total", 10.0)
    return status


def _check_slo_burn_rate(ctx: RuleContext) -> Optional[Dict[str, Any]]:
    status = run_slo_status(ctx)
    if status is None:
        return None  # off until an error budget is declared
    # The windows double as the anti-flap mechanism (for_s stays 0): the
    # fast window makes the alert responsive, the slow window keeps one
    # spike from firing it — both must burn.
    if ctx.stats is not None:
        run_label = str(ctx.run.id)
        ctx.stats.gauge(
            labeled_key("slo_burn_fast", run=run_label, slo=status["name"]),
            status["fast_burn"],
        )
        ctx.stats.gauge(
            labeled_key("slo_burn_slow", run=run_label, slo=status["name"]),
            status["slow_burn"],
        )
        ctx.stats.gauge(
            labeled_key(
                "slo_budget_remaining", run=run_label, slo=status["name"]
            ),
            status["budget_remaining"],
        )
    if status["total_slow"] < status["min_total"]:
        return None  # not enough traffic to judge a budget
    threshold = status["burn_threshold"]
    if status["fast_burn"] <= threshold or status["slow_burn"] <= threshold:
        return None
    return {
        "value": float(status["fast_burn"]),
        "message": (
            f"SLO '{status['name']}' burning {status['fast_burn']:.1f}x "
            f"budget over {status['fast_window_s']:.0f}s and "
            f"{status['slow_burn']:.1f}x over {status['slow_window_s']:.0f}s "
            f"(target {status['target']:.3f}, "
            f"{status['budget_remaining']*100:.0f}% budget left)"
        ),
        "slo": status["name"],
        "target": status["target"],
        "fast_burn": status["fast_burn"],
        "slow_burn": status["slow_burn"],
        "budget_remaining": status["budget_remaining"],
        "bad_series": status["bad_series"],
        "total_series": status["total_series"],
    }


def default_rules() -> List[AlertRule]:
    """The built-in catalog; ``for_s`` defaults are starting points — every
    value here is overridable per run (declarations) and per deployment
    (env knobs)."""
    return [
        AlertRule(
            "run_stalled",
            AlertSeverity.CRITICAL,
            0.0,  # stall_after_s already IS a hold-down
            _check_run_stalled,
            "gang alive (fresh heartbeats) but no forward progress",
        ),
        AlertRule(
            "gang_straggler",
            AlertSeverity.WARNING,
            0.0,
            _check_gang_straggler,
            "one host's step lags the gang median",
        ),
        AlertRule(
            "heartbeat_stale",
            AlertSeverity.CRITICAL,
            0.0,
            _check_heartbeat_stale,
            "no heartbeat from any gang process past the threshold",
        ),
        AlertRule(
            "goodput_low",
            AlertSeverity.WARNING,
            30.0,
            _check_goodput_low,
            "goodput ratio below the declared SLO floor",
        ),
        AlertRule(
            "mfu_low",
            AlertSeverity.WARNING,
            30.0,
            _check_mfu_low,
            "MFU below the declared SLO floor",
        ),
        AlertRule(
            "serving_ttft_p99",
            AlertSeverity.WARNING,
            30.0,
            _check_serving_ttft_p99,
            "serving TTFT p99 above the declared latency SLO",
        ),
        AlertRule(
            "steady_state_compiles",
            AlertSeverity.WARNING,
            0.0,
            _check_steady_state_compiles,
            "XLA recompilation observed after serving warmup",
        ),
        AlertRule(
            "compile_cache_miss",
            AlertSeverity.INFO,
            0.0,
            _check_compile_cache_miss,
            "persistent compile cache mostly missing",
        ),
        AlertRule(
            "slo_burn_rate",
            AlertSeverity.CRITICAL,
            0.0,  # the fast+slow window pair IS the hold-down
            _check_slo_burn_rate,
            "error budget burning above threshold on both the fast and "
            "slow windows",
        ),
    ]


class AlertEngine:
    """Ticks the rule catalog over live runs; owns the alert lifecycle.

    State lives in the registry ``alerts`` table, not in memory — a
    restarted control plane resumes hold-downs and open episodes instead
    of re-paging for everything it already knew about.
    """

    def __init__(
        self,
        registry: RunRegistry,
        *,
        stats: Any = None,
        metrics: Any = None,
        auditor: Any = None,
        rules: Optional[List[AlertRule]] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        self.registry = registry
        self.stats = stats
        self.metrics = metrics
        self.auditor = auditor
        self.rules = list(rules) if rules is not None else default_rules()
        self.interval_s = (
            interval_s
            if interval_s is not None
            else knob_float("POLYAXON_TPU_ALERT_INTERVAL_S")
        )
        self.last_tick_at: float = 0.0
        self.ticks: int = 0
        self.eval_errors: int = 0
        self._last_eval: Dict[int, float] = {}

    # -- per-tick entrypoints --------------------------------------------------
    def evaluate(
        self, run_or_handle: Any, *, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """One evaluation pass for one live run.  Called from the scheduler
        monitor task every tick; internally throttled to ``interval_s`` per
        run so rule evaluation stays off the hot path.  Returns the state
        transitions it performed (empty on throttled/steady ticks)."""
        run_id = getattr(run_or_handle, "run_id", run_or_handle)
        now = now if now is not None else time.time()
        last = self._last_eval.get(run_id, 0.0)
        if self.interval_s > 0 and now - last < self.interval_s:
            return []
        self._last_eval[run_id] = now
        self.last_tick_at = now
        self.ticks += 1
        run = self.registry.get_run(run_id)
        if run is None:
            return []
        ctx = RuleContext(
            self.registry, run, stats=self.stats, metrics=self.metrics, now=now
        )
        current = {
            row["rule"]: row for row in self.registry.get_alerts(run_id)
        }
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                transitions.extend(self._step(ctx, rule, current.get(rule.name)))
            except Exception:
                self.eval_errors += 1
                if self.stats is not None:
                    self.stats.incr("alert_eval_errors")
                logger.warning(
                    "Alert rule %r failed for run %d",
                    rule.name,
                    run_id,
                    exc_info=True,
                )
        return transitions

    def _step(
        self,
        ctx: RuleContext,
        rule: AlertRule,
        row: Optional[Dict[str, Any]],
    ) -> List[Dict[str, Any]]:
        """Advance one (run, rule) pair through the lifecycle state machine."""
        run_id = ctx.run.id
        state = row["state"] if row else None
        violation = (
            rule.check(ctx) if ctx.enabled(rule.name) else None
        )
        for_s = ctx.param(rule.name, "for_s", rule.for_s)
        out: List[Dict[str, Any]] = []

        if violation is not None:
            value = float(violation.pop("value", 0.0))
            message = str(violation.pop("message", rule.name))
            attrs = violation  # whatever the check left behind
            if state == AlertState.FIRING:
                self._gauge(rule, run_id, GAUGE_FIRING)
                return out  # steady firing: no row churn, no re-notify
            if state == AlertState.PENDING:
                if ctx.now - (row["pending_since"] or ctx.now) >= for_s:
                    fired = self.registry.upsert_alert(
                        run_id,
                        rule.name,
                        state=AlertState.FIRING,
                        severity=rule.severity,
                        message=message,
                        value=value,
                        for_s=for_s,
                        episodes=(row["episodes"] or 0) + 1,
                        fired_at=ctx.now,
                        resolved_at=None,
                        attrs=attrs,
                        now=ctx.now,
                    )
                    out.append(fired)
                    self._gauge(rule, run_id, GAUGE_FIRING)
                    self._notify(EventTypes.ALERT_FIRING, ctx.run, fired)
                else:
                    self._gauge(rule, run_id, GAUGE_PENDING)
                return out
            # inactive (no row, or resolved) → pending; a zero hold-down
            # fires in the same tick it pends, one transition row each.
            pending = self.registry.upsert_alert(
                run_id,
                rule.name,
                state=AlertState.PENDING,
                severity=rule.severity,
                message=message,
                value=value,
                for_s=for_s,
                pending_since=ctx.now,
                fired_at=None if row is None else row.get("fired_at"),
                resolved_at=None,
                attrs=attrs,
                now=ctx.now,
            )
            out.append(pending)
            self._gauge(rule, run_id, GAUGE_PENDING)
            if for_s <= 0:
                fired = self.registry.upsert_alert(
                    run_id,
                    rule.name,
                    state=AlertState.FIRING,
                    severity=rule.severity,
                    message=message,
                    value=value,
                    for_s=for_s,
                    episodes=(row["episodes"] if row else 0) + 1,
                    fired_at=ctx.now,
                    resolved_at=None,
                    attrs=attrs,
                    now=ctx.now,
                )
                out.append(fired)
                self._gauge(rule, run_id, GAUGE_FIRING)
                self._notify(EventTypes.ALERT_FIRING, ctx.run, fired)
            return out

        # healthy
        if state == AlertState.FIRING:
            resolved = self.registry.upsert_alert(
                run_id,
                rule.name,
                state=AlertState.RESOLVED,
                severity=rule.severity,
                message=f"{rule.name} recovered",
                value=None,
                for_s=for_s,
                resolved_at=ctx.now,
                attrs=row.get("attrs") or None,
                now=ctx.now,
            )
            out.append(resolved)
            self._gauge(rule, run_id, GAUGE_OK)
            self._notify(EventTypes.ALERT_RESOLVED, ctx.run, resolved)
        elif state == AlertState.PENDING:
            # Flap suppressed: recovered inside the hold-down — drop the
            # row entirely, nobody was ever paged.
            self.registry.delete_alert(run_id, rule.name)
            self._gauge(rule, run_id, GAUGE_OK)
        return out

    def finalize(
        self, run_id: int, *, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """Terminal-run cleanup: resolve open episodes, drop pendings, zero
        gauges — a finished run must not keep paging (the
        terminal-mid-episode discipline of ``run_stall_age_s``)."""
        now = now if now is not None else time.time()
        out: List[Dict[str, Any]] = []
        run = self.registry.get_run(run_id)
        for row in self.registry.get_alerts(run_id):
            if row["state"] == AlertState.FIRING:
                resolved = self.registry.upsert_alert(
                    run_id,
                    row["rule"],
                    state=AlertState.RESOLVED,
                    severity=row["severity"],
                    message=f"{row['rule']}: run finished",
                    value=None,
                    for_s=row["for_s"],
                    resolved_at=now,
                    attrs=row.get("attrs") or None,
                    now=now,
                )
                out.append(resolved)
                self._notify(EventTypes.ALERT_RESOLVED, run, resolved)
            elif row["state"] == AlertState.PENDING:
                self.registry.delete_alert(run_id, row["rule"])
            self._gauge_raw(row["rule"], run_id, row["severity"], GAUGE_OK)
        self._last_eval.pop(run_id, None)
        return out

    def evaluate_regression(
        self,
        run: Run,
        folded: Dict[str, Dict[str, Any]],
        *,
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Cross-run regression verdict for a *completed* run.

        Called once from the run-terminal hook, after the run's summary
        series were folded into their (project, kind) baselines —
        ``folded`` is :func:`stats.tsdb.fold_run_baselines`'s result,
        whose per-series entries carry the baseline as it stood *before*
        this run.  A ``metric_regression`` alert row fires when any
        series landed beyond k·σ below its baseline (these are all
        higher-is-better throughput metrics).  The row stays FIRING —
        terminal runs are never re-evaluated, so the verdict is durable:
        exactly what the canary promote/rollback comparator reads.
        """
        now = now if now is not None else time.time()
        ctx = RuleContext(
            self.registry, run, stats=self.stats, metrics=self.metrics, now=now
        )
        if not ctx.enabled("metric_regression") or not folded:
            return None
        k = ctx.param("metric_regression", "k", 3.0)
        min_runs = ctx.param("metric_regression", "min_runs", 3.0)
        # σ floor as a fraction of the mean: identical early runs would
        # otherwise make any deviation register as infinitely improbable.
        std_floor_frac = ctx.param("metric_regression", "min_std_frac", 0.05)
        regressions: List[Dict[str, Any]] = []
        for series, fold in folded.items():
            prior_mean = fold.get("prior_mean")
            if prior_mean is None or fold.get("prior_count", 0) < min_runs:
                continue
            std = max(
                fold.get("prior_std") or 0.0,
                abs(prior_mean) * std_floor_frac,
                1e-12,
            )
            z = (fold["value"] - prior_mean) / std
            if z < -k:
                regressions.append({
                    "series": series,
                    "value": fold["value"],
                    "baseline_mean": prior_mean,
                    "baseline_std": fold.get("prior_std"),
                    "baseline_runs": fold.get("prior_count"),
                    "z": round(z, 3),
                })
        if not regressions:
            return None
        worst = min(regressions, key=lambda r: r["z"])
        row = self.registry.upsert_alert(
            run.id,
            "metric_regression",
            state=AlertState.FIRING,
            severity=AlertSeverity.WARNING,
            message=(
                f"{worst['series']} {worst['value']:.4g} is "
                f"{abs(worst['z']):.1f}σ below its "
                f"({run.project or 'default'}, {run.kind}) baseline "
                f"{worst['baseline_mean']:.4g} "
                f"(k={k:.1f}, {len(regressions)} series regressed)"
            ),
            value=float(worst["z"]),
            for_s=0.0,
            episodes=1,
            fired_at=now,
            resolved_at=None,
            attrs={"regressions": regressions, "k": k},
            now=now,
        )
        self._notify(EventTypes.ALERT_FIRING, run, row)
        return row

    # -- fan-out ---------------------------------------------------------------
    def _gauge(self, rule: AlertRule, run_id: int, value: float) -> None:
        self._gauge_raw(rule.name, run_id, rule.severity, value)

    def _gauge_raw(
        self, rule: str, run_id: int, severity: str, value: float
    ) -> None:
        if self.stats is not None:
            self.stats.gauge(alert_gauge_key(rule, run_id, severity), value)

    def _notify(
        self, event_type: str, run: Optional[Run], row: Dict[str, Any]
    ) -> None:
        if self.auditor is None:
            return
        payload = {
            "run_id": row["run_id"],
            "run_name": getattr(run, "name", None),
            "project": getattr(run, "project", None),
            "rule": row["rule"],
            "state": row["state"],
            "severity": row["severity"],
            "message": row["message"],
            "value": row["value"],
            "for_s": row["for_s"],
            "episodes": row["episodes"],
            "pending_since": row["pending_since"],
            "fired_at": row["fired_at"],
            "resolved_at": row["resolved_at"],
            "attrs": row.get("attrs") or {},
        }
        try:
            self.auditor.record(event_type, **payload)
        except Exception:
            logger.warning(
                "Alert notification failed for %s/%s",
                row["run_id"],
                row["rule"],
                exc_info=True,
            )

    # -- introspection (health probe / status page) ----------------------------
    def status(self) -> Dict[str, Any]:
        return {
            "rules": [r.name for r in self.rules],
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "eval_errors": self.eval_errors,
            "last_tick_at": self.last_tick_at,
        }
