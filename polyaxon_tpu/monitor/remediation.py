"""Remediation policy: the detection→action loop.

The platform's senses (stall/straggler detectors, goodput ledger), mouth
(alert engine firing edges), and hands (the run command bus) all exist —
this module is the reflex arc between them.  It subscribes to alert
transitions and gang terminal states from the scheduler's monitor tick
and executes typed actions through existing machinery:

- ``checkpoint_now`` — a critical alert (``run_stalled`` by default) on a
  run that declares checkpointing gets a gang-wide ``checkpoint-now``
  command; workers force-save and ack with the saved step.
- ``resume``/``restart`` — a FAILED gang with restart budget relaunches
  from its latest *complete* async checkpoint (finalize markers, so a
  torn save left by the dead process never answers) with exponential
  backoff, instead of the old blind restart from step 0.
- ``evict`` — a firing ``gang_straggler`` (opt-in: eviction is
  destructive) checkpoints the gang, kills the straggler host, and
  records an elastic topology override in the run's meta; the resume
  path then re-forms the gang on the smaller data-parallel mesh.

Every action is a registry row (lifecycle + cascade + retention like
commands/alerts), an audit event, and a
``remediation_total{action,outcome}`` counter — the run's timeline
explains both action and deliberate inaction (budget exhausted, topology
not shrinkable → SKIPPED rows).

Parity: the reference's restart policies (``polypod/templates/
restart_policy.py``) decided *whether* to relaunch; this layer also
decides *from where* and *on what topology*.
"""

from __future__ import annotations

import dataclasses
import logging
import signal as _signal
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_int, knob_str
from polyaxon_tpu.db.registry import (
    CommandStatus,
    RemediationStatus,
    RunRegistry,
    command_ack_attrs,
)
from polyaxon_tpu.events.registry import EventTypes
from polyaxon_tpu.runtime.checkpoint import latest_complete_step
from polyaxon_tpu.stats import get_stats
from polyaxon_tpu.stats.metrics import labeled_key

logger = logging.getLogger(__name__)

#: Mesh axes a shrunken gang may fold its lost hosts into, best first —
#: data-parallel-ish axes replicate state, so shrinking them never
#: orphans a parameter shard the way shrinking a tensor axis would.
_SHRINK_AXES = ("data", "replica", "fsdp")


def shrink_mesh_axes(
    mesh_axes: Dict[str, int],
    dcn_axes: Optional[Dict[str, int]],
    old_hosts: int,
    new_hosts: int,
) -> Optional[Tuple[Dict[str, int], Dict[str, int]]]:
    """Re-plan a gang's mesh for fewer hosts by shrinking one data-like
    axis proportionally; None when no axis divides cleanly (a pure
    tensor-parallel gang cannot lose a host and keep its sharding)."""
    if new_hosts < 1 or new_hosts >= old_hosts:
        return None
    axes = dict(mesh_axes)
    candidates = [n for n in _SHRINK_AXES if n in axes]
    candidates += [n for n in axes if n not in candidates]
    for name in candidates:
        size = int(axes[name])
        if size <= 1:
            continue
        if (size * new_hosts) % old_hosts != 0:
            continue
        new_size = size * new_hosts // old_hosts
        if new_size < 1:
            continue
        axes[name] = new_size
        dcn = dict(dcn_axes or {})
        if name in dcn:
            # The DCN (cross-slice) share of the axis shrinks proportionally
            # when it divides cleanly; otherwise clamp — it can never exceed
            # the mesh axis it splits.
            d = int(dcn[name])
            if d > 1 and (d * new_hosts) % old_hosts == 0:
                dcn[name] = max(1, d * new_hosts // old_hosts)
            else:
                dcn[name] = min(d, new_size)
        return axes, dcn
    return None


class RemediationEngine:
    """Alert-edge + terminal-state driven action executor.

    The scheduler's monitor tick feeds it (``on_transitions`` with the
    alert engine's transition rows, ``tick`` to advance multi-phase
    actions, ``on_gang_failed`` for the relaunch decision); it acts only
    through injected seams — ``sender`` (the orchestrator's
    ``send_command``) and the gang handle's process refs — so it unit
    tests without a live gang.

    Env knobs (all ``POLYAXON_TPU_REMEDIATION_*``):

    - ``ENABLED`` (default 1): master switch; off = legacy blind-restart
      behavior, no rows, no audit.
    - ``BUDGET`` (default 16): max non-skipped actions per run; exhausted
      → a SKIPPED row and no relaunch.
    - ``BACKOFF_BASE_S`` (default: the plan's ``backoff_seconds``) and
      ``BACKOFF_MAX_S`` (default 300): relaunch waits
      ``min(max, base * 2**restarts)``.
    - ``CHECKPOINT_ALERTS`` (default ``run_stalled``): comma-separated
      rules whose firing edge triggers ``checkpoint-now``.
    - ``EVICT`` (default 0): opt-in straggler eviction.
    - ``COMMAND_TIMEOUT_S`` (default 30): how long an issued command may
      stay unresolved before the action fails (or eviction proceeds
      without its checkpoint).
    """

    def __init__(
        self,
        registry: RunRegistry,
        *,
        stats: Any = None,
        auditor: Any = None,
        sender: Optional[Callable[..., Dict[str, Any]]] = None,
    ) -> None:
        self.registry = registry
        self.stats = stats if stats is not None else get_stats()
        self.auditor = auditor
        self.sender = sender
        self.enabled = knob_bool("POLYAXON_TPU_REMEDIATION_ENABLED")
        self.budget = knob_int("POLYAXON_TPU_REMEDIATION_BUDGET")
        base = knob_str("POLYAXON_TPU_REMEDIATION_BACKOFF_BASE_S")
        self.backoff_base_s: Optional[float] = float(base) if base else None
        self.backoff_max_s = knob_float("POLYAXON_TPU_REMEDIATION_BACKOFF_MAX_S")
        self.checkpoint_rules = {
            r.strip()
            for r in knob_str(
                "POLYAXON_TPU_REMEDIATION_CHECKPOINT_ALERTS"
            ).split(",")
            if r.strip()
        }
        self.evict_enabled = knob_bool("POLYAXON_TPU_REMEDIATION_EVICT")
        self.command_timeout_s = knob_float(
            "POLYAXON_TPU_REMEDIATION_COMMAND_TIMEOUT_S"
        )
        self.drain_rules = {
            r.strip()
            for r in knob_str("POLYAXON_TPU_REMEDIATION_DRAIN_ALERTS").split(",")
            if r.strip()
        }
        #: Serving fleets that asked for alert-driven drain/replace
        #: (:meth:`register_fleet`); a firing drain rule on one of their
        #: replica runs opens a drain_replace operation.
        self._fleets: List[Any] = []
        self.actions = 0
        self.errors = 0
        self.last_action_at: Optional[float] = None

    # -- serving fleets --------------------------------------------------------
    def register_fleet(self, fleet: Any) -> None:
        if fleet not in self._fleets:
            self._fleets.append(fleet)

    def unregister_fleet(self, fleet: Any) -> None:
        if fleet in self._fleets:
            self._fleets.remove(fleet)

    def _fleet_for(self, run_id: int) -> Optional[Any]:
        for fleet in self._fleets:
            try:
                if fleet.handles_run(run_id):
                    return fleet
            except Exception:
                continue
        return None

    # -- bookkeeping ----------------------------------------------------------
    def _count(self, action: str, outcome: str) -> None:
        try:
            self.stats.incr(
                labeled_key("remediation_total", action=action, outcome=outcome)
            )
        except Exception:
            pass
        self.actions += 1
        self.last_action_at = time.time()

    def _audit(self, run_id: int, action: str, outcome: str, **attrs: Any) -> None:
        if self.auditor is None:
            return
        try:
            self.auditor.record(
                EventTypes.EXPERIMENT_REMEDIATION,
                run_id=run_id,
                action=action,
                outcome=outcome,
                **attrs,
            )
        except Exception:
            logger.warning("remediation audit failed", exc_info=True)

    def _budget_left(self, run_id: int) -> int:
        spent = self.registry.count_remediations(
            run_id,
            statuses=(
                RemediationStatus.PENDING,
                RemediationStatus.IN_PROGRESS,
                RemediationStatus.SUCCEEDED,
                RemediationStatus.FAILED,
            ),
        )
        return self.budget - spent

    def _declared_save_every(self, run_id: int) -> int:
        run = self.registry.get_run(run_id)
        if run is None:
            return 0
        decls = run.spec_data.get("declarations") or {}
        try:
            return int(decls.get("save_every") or 0)
        except (TypeError, ValueError):
            return 0

    def _open(self, run_id: int, action: str) -> List[Dict[str, Any]]:
        return [
            r
            for r in self.registry.get_remediations(run_id, action=action)
            if r["status"] in RemediationStatus.OPEN
        ]

    # -- alert edges ----------------------------------------------------------
    def on_transitions(self, handle: Any, transitions: List[Dict[str, Any]]) -> None:
        """React to the alert engine's transition rows for one gang."""
        if not self.enabled or not transitions:
            return
        for row in transitions:
            if row.get("state") != "firing":
                continue
            rule = str(row.get("rule") or "")
            try:
                if rule in self.checkpoint_rules:
                    self._on_checkpoint_rule(handle, rule)
                if rule == "gang_straggler" and self.evict_enabled:
                    self._on_straggler(handle, rule, row.get("attrs") or {})
                if rule in self.drain_rules:
                    self._on_drain_rule(handle, rule)
            except Exception:
                self.errors += 1
                logger.warning(
                    "remediation reaction to %s failed for run %s",
                    rule,
                    handle.run_id,
                    exc_info=True,
                )

    def _issue_checkpoint_now(
        self, handle: Any, rem: Dict[str, Any], reason: str
    ) -> Optional[str]:
        """Send the gang-wide command; returns its uuid (None = send
        failed, the row is already marked FAILED)."""
        try:
            cmd = self.sender(
                handle.run_id,
                "checkpoint-now",
                payload={"reason": reason},
                actor="remediation",
            )
        except Exception as exc:
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.FAILED,
                message=f"command send failed: {exc}",
            )
            self._count("checkpoint_now", "failed")
            return None
        if cmd["status"] in CommandStatus.TERMINAL:
            # EXPIRED straight from send: the run is already done.
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.FAILED,
                message=f"command {cmd['status']} at send",
                attrs={"command_uuid": cmd["uuid"]},
            )
            self._count("checkpoint_now", "failed")
            return None
        self.registry.update_remediation(
            rem["id"],
            attrs={
                "command_uuid": cmd["uuid"],
                "deadline": time.time() + self.command_timeout_s,
            },
        )
        return cmd["uuid"]

    def _on_checkpoint_rule(self, handle: Any, rule: str) -> None:
        run_id = handle.run_id
        if self.sender is None or self._declared_save_every(run_id) <= 0:
            return  # nothing to fence — the run doesn't checkpoint
        if self._open(run_id, "checkpoint_now") or self._budget_left(run_id) <= 0:
            return
        rem = self.registry.add_remediation(
            run_id,
            "checkpoint_now",
            trigger=rule,
            status=RemediationStatus.IN_PROGRESS,
            attrs={"alert": rule},
        )
        if self._issue_checkpoint_now(handle, rem, rule) is not None:
            self._audit(run_id, "checkpoint_now", "issued", trigger=rule)
            self._count("checkpoint_now", "issued")

    def _on_drain_rule(self, handle: Any, rule: str) -> None:
        """A drain-class alert (stale heartbeat, TTFT SLO burn) fired on
        a run that belongs to a registered serving fleet: open a
        ``drain_replace`` operation and hand it to the fleet — the fleet's
        ``poll()`` advances the phases and closes the row."""
        run_id = handle.run_id
        fleet = self._fleet_for(run_id)
        if fleet is None:
            return  # not a fleet replica — drain means nothing here
        if self._open(run_id, "drain_replace") or self._budget_left(run_id) <= 0:
            return
        rem = self.registry.add_remediation(
            run_id,
            "drain_replace",
            trigger=rule,
            status=RemediationStatus.IN_PROGRESS,
            attrs={"alert": rule, "phase": "draining"},
        )
        started = False
        try:
            started = bool(
                fleet.request_drain_replace(run_id, rem["id"], rule)
            )
        except Exception as exc:
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.FAILED,
                message=f"fleet drain request failed: {exc}",
            )
            self._count("drain_replace", "failed")
            return
        if not started:
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.SKIPPED,
                message="fleet declined (unknown replica or already draining)",
            )
            self._count("drain_replace", "skipped")
            return
        self._audit(run_id, "drain_replace", "started", trigger=rule)
        self._count("drain_replace", "started")

    def _on_straggler(self, handle: Any, rule: str, attrs: Dict[str, Any]) -> None:
        run_id = handle.run_id
        plan = handle.plan
        if plan.num_hosts <= 1:
            return
        if self._open(run_id, "evict") or self._budget_left(run_id) <= 0:
            return
        stragglers = attrs.get("stragglers") or []
        victim = None
        worst = -1
        for s in stragglers:
            lag = int(s.get("lag_steps") or 0)
            if lag > worst:
                worst, victim = lag, int(s.get("process_id", -1))
        if victim is None or victim < 0 or victim not in handle.processes:
            return
        shrunk = shrink_mesh_axes(
            plan.mesh_axes, plan.dcn_axes, plan.num_hosts, plan.num_hosts - 1
        )
        if shrunk is None:
            self.registry.add_remediation(
                run_id,
                "evict",
                trigger=rule,
                status=RemediationStatus.SKIPPED,
                message="mesh not shrinkable by one host",
                attrs={"process_id": victim, "mesh_axes": dict(plan.mesh_axes)},
            )
            self._count("evict", "skipped")
            return
        rem = self.registry.add_remediation(
            run_id,
            "evict",
            trigger=rule,
            status=RemediationStatus.IN_PROGRESS,
            attrs={"process_id": victim, "lag_steps": worst, "phase": "checkpoint"},
        )
        self._audit(run_id, "evict", "started", process_id=victim, lag_steps=worst)
        self._count("evict", "started")
        # Fence state first when the run checkpoints (excluding the victim:
        # a straggler wedged in a collective can't save — peers can).
        if self.sender is not None and self._declared_save_every(run_id) > 0:
            if self._issue_checkpoint_now(handle, rem, rule) is not None:
                return  # kill proceeds from tick() once the command resolves
            rem = self.registry.get_remediation(rem["id"])
            if rem is None or rem["status"] in RemediationStatus.TERMINAL:
                return
        self._finish_evict(handle, rem)

    def _finish_evict(self, handle: Any, rem: Dict[str, Any]) -> None:
        """Kill the victim and persist the elastic topology override —
        the gang fails, and the resume path relaunches it one host
        smaller."""
        run_id = handle.run_id
        plan = handle.plan
        victim = int(rem["attrs"].get("process_id", -1))
        new_hosts = plan.num_hosts - 1
        shrunk = shrink_mesh_axes(
            plan.mesh_axes, plan.dcn_axes, plan.num_hosts, new_hosts
        )
        if shrunk is None:
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.FAILED,
                message="mesh not shrinkable by one host",
            )
            self._count("evict", "failed")
            return
        mesh_axes, dcn_axes = shrunk
        elastic = {
            "num_hosts": new_hosts,
            "mesh_axes": mesh_axes,
            "dcn_axes": dcn_axes,
            "evicted": [victim],
            "at": time.time(),
        }
        self.registry.merge_run_meta(run_id, elastic=elastic)
        ref = handle.processes.get(victim)
        try:
            if ref is not None and ref.poll() is None:
                ref.signal(_signal.SIGKILL)
        except Exception:
            logger.warning(
                "evict: signalling proc %d of run %s failed", victim, run_id,
                exc_info=True,
            )
        self.registry.update_remediation(
            rem["id"],
            status=RemediationStatus.SUCCEEDED,
            message=f"evicted proc {victim}; gang re-forms on {new_hosts} host(s)",
            attrs={"phase": "killed", "elastic": elastic},
        )
        if self.auditor is not None:
            try:
                self.auditor.record(
                    EventTypes.EXPERIMENT_EVICTED,
                    run_id=run_id,
                    process_id=victim,
                    num_hosts=new_hosts,
                    mesh_axes=mesh_axes,
                )
            except Exception:
                pass
        self._count("evict", "succeeded")

    # -- per-tick advancement -------------------------------------------------
    def tick(self, handle: Any, now: Optional[float] = None) -> None:
        """Advance multi-phase actions (command resolution, timeouts)."""
        if not self.enabled:
            return
        now = now if now is not None else time.time()
        run_id = handle.run_id
        for rem in self._open(run_id, "checkpoint_now"):
            self._tick_checkpoint_now(rem, now)
        for rem in self._open(run_id, "evict"):
            self._tick_evict(handle, rem, now)

    def _resolve_command(
        self, rem: Dict[str, Any], now: float
    ) -> Optional[Tuple[str, Optional[int]]]:
        """(outcome, saved_step) for the row's issued command, or None
        while still pending inside its deadline."""
        uuid = rem["attrs"].get("command_uuid")
        if not uuid:
            return ("failed", None)
        cmd = self.registry.get_command(str(uuid))
        if cmd is None:
            return ("failed", None)
        if cmd["status"] == CommandStatus.COMPLETE:
            steps = [
                command_ack_attrs(v).get("step")
                for v in cmd["acks"].values()
            ]
            steps = [int(s) for s in steps if s is not None]
            return ("succeeded", max(steps) if steps else None)
        if cmd["status"] in CommandStatus.TERMINAL:
            return ("failed", None)
        if now > float(rem["attrs"].get("deadline") or 0):
            return ("timeout", None)
        return None

    def _tick_checkpoint_now(self, rem: Dict[str, Any], now: float) -> None:
        resolved = self._resolve_command(rem, now)
        if resolved is None:
            return
        outcome, saved_step = resolved
        if outcome == "succeeded":
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.SUCCEEDED,
                message=(
                    f"gang checkpointed at step {saved_step}"
                    if saved_step is not None
                    else "gang checkpointed"
                ),
                attrs={"saved_step": saved_step},
            )
            self._audit(
                rem["run_id"], "checkpoint_now", "succeeded", saved_step=saved_step
            )
            self._count("checkpoint_now", "succeeded")
        else:
            self.registry.update_remediation(
                rem["id"],
                status=RemediationStatus.FAILED,
                message=f"checkpoint-now {outcome}",
            )
            self._count("checkpoint_now", "failed")

    def _tick_evict(self, handle: Any, rem: Dict[str, Any], now: float) -> None:
        if rem["attrs"].get("phase") != "checkpoint":
            return
        if rem["attrs"].get("command_uuid"):
            resolved = self._resolve_command(rem, now)
            if resolved is None:
                return  # checkpoint still in flight
            # Timeout/failure doesn't abort the eviction: a wedged gang
            # may be unable to save — proceed with the last durable step.
        self._finish_evict(handle, rem)

    # -- terminal states ------------------------------------------------------
    def on_gang_failed(self, run: Any, handle: Any) -> Optional[Dict[str, Any]]:
        """The relaunch decision for a FAILED gang with restart budget.

        Returns ``{"backoff_s", "from_step", "message"}`` to relaunch
        (the scheduler keeps ``run.restarts`` monotonic and rotates
        reports), or None to let the run fail (remediation budget
        exhausted — recorded as a SKIPPED row so the timeline says why).
        """
        plan = handle.plan
        base = (
            self.backoff_base_s
            if self.backoff_base_s is not None
            else float(plan.backoff_seconds or 0.0)
        )
        attempt = run.restarts + 1
        if not self.enabled:
            # Legacy behavior, verbatim: fixed backoff, blind restart.
            return {
                "backoff_s": base,
                "from_step": None,
                "message": f"gang failed; restart {attempt}/{plan.max_restarts}",
            }
        if self._budget_left(run.id) <= 0:
            self.registry.add_remediation(
                run.id,
                "resume",
                trigger="gang_failed",
                status=RemediationStatus.SKIPPED,
                message=f"remediation budget ({self.budget}) exhausted",
            )
            self._count("resume", "skipped")
            return None
        try:
            from_step = latest_complete_step(handle.paths.checkpoints)
        except Exception:
            from_step = None
        backoff = min(self.backoff_max_s, base * (2 ** run.restarts)) if base > 0 else 0.0
        action = "resume" if from_step is not None else "restart"
        self.registry.add_remediation(
            run.id,
            action,
            trigger="gang_failed",
            status=RemediationStatus.SUCCEEDED,
            message=(
                f"resuming from checkpoint step {from_step}"
                if from_step is not None
                else "no complete checkpoint; restarting from step 0"
            ),
            attrs={"from_step": from_step, "attempt": attempt, "backoff_s": backoff},
        )
        if from_step is not None and self.auditor is not None:
            try:
                self.auditor.record(
                    EventTypes.EXPERIMENT_RESUMED,
                    run_id=run.id,
                    from_step=from_step,
                    attempt=attempt,
                )
            except Exception:
                pass
        self._audit(run.id, action, "succeeded", attempt=attempt, from_step=from_step)
        self._count(action, "succeeded")
        where = (
            f"resume from step {from_step}" if from_step is not None else "restart"
        )
        return {
            "backoff_s": backoff,
            "from_step": from_step,
            "message": (
                f"gang failed; {where} {attempt}/{plan.max_restarts}"
                f" (backoff {backoff:.1f}s)"
            ),
        }

    def apply_elastic_plan(self, run: Any, plan: Any) -> Any:
        """Apply a recorded eviction's topology override to a freshly
        compiled plan (``experiments_start`` calls this on every launch so
        the override survives further restarts)."""
        elastic = (getattr(run, "meta", None) or {}).get("elastic")
        if not elastic:
            return plan
        try:
            new_hosts = int(elastic.get("num_hosts") or 0)
        except (TypeError, ValueError):
            return plan
        if new_hosts < 1 or new_hosts >= plan.num_hosts:
            return plan
        return dataclasses.replace(
            plan,
            num_hosts=new_hosts,
            mesh_axes=dict(elastic.get("mesh_axes") or plan.mesh_axes),
            dcn_axes=dict(elastic.get("dcn_axes") or {}),
        )

    def finalize(self, run_id: int) -> None:
        """Close open action rows when the run reaches a terminal state."""
        self.registry.expire_remediations(run_id)

    def status(self) -> Dict[str, Any]:
        """Introspection for the health probe and the API."""
        return {
            "enabled": self.enabled,
            "evict_enabled": self.evict_enabled,
            "budget": self.budget,
            "actions": self.actions,
            "errors": self.errors,
            "last_action_at": self.last_action_at,
            "checkpoint_rules": sorted(self.checkpoint_rules),
            "drain_rules": sorted(self.drain_rules),
            "fleets": len(self._fleets),
            "backoff_max_s": self.backoff_max_s,
        }
