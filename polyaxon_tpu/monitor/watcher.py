"""Gang observation: tail report files, reconcile process liveness.

Parity: the reference's observation stack — the ocular pod watch loop
(``monitor_statuses/monitor.py:87-200``), the k8s events handlers writing
job-status rows (``k8s_events_handlers/tasks/statuses.py:36-288``), and the
sidecar liveness reconcile (``sidecar/sidecar/__main__.py:39-58``).
TPU-native: statuses/metrics/logs arrive as appended JSON lines in the run's
``reports/`` dir; liveness is the subprocess table itself.  Both sources are
reconciled into the registry, statuses gated by the job lifecycle, and the
gang roll-up (``gang_status``) becomes the experiment status.
"""

from __future__ import annotations

import json
import logging
import statistics
import time
from typing import Any, Dict, List, Optional

from polyaxon_tpu.conf.knobs import knob_default, knob_float, knob_int
from polyaxon_tpu.db.registry import RunRegistry
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.lifecycles.registry import gang_status
from polyaxon_tpu.spawner.local import GangHandle
from polyaxon_tpu.stats.metrics import labeled_key
from polyaxon_tpu.tracking.trace import get_tracer

logger = logging.getLogger(__name__)

#: Per-poll read budget per process file — bounds the watcher's memory when
#: it falls behind a chatty gang (the tail used to be slurped whole).
DEFAULT_POLL_BYTES = knob_default("POLYAXON_TPU_WATCHER_POLL_BYTES")


def anomaly_status(
    registry: RunRegistry,
    run_id: int,
    *,
    now: Optional[float] = None,
    stall_after_s: Optional[float] = None,
    straggler_lag_steps: Optional[float] = None,
    heartbeat_fresh_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Live gang-level stall/straggler roll-up over ingested progress rows.

    Pure read — shared by the watcher's per-tick detector (which persists
    transitions as anomaly rows) and the API's run-status payload (which
    wants the current truth without waiting for a monitor tick).

    *Stalled* means alive-but-stuck: every liveness signal is fresh
    (heartbeats within ``heartbeat_fresh_s``) but the newest progress beat
    across the whole gang is older than ``stall_after_s`` — the state
    ``reconcile()`` cannot see, because every process is still running.
    *Straggler* means one host's step lags the gang's median step by
    ``straggler_lag_steps`` or more.
    """
    now = now if now is not None else time.time()
    if stall_after_s is None:
        stall_after_s = knob_float("POLYAXON_TPU_STALL_AFTER_S")
    if straggler_lag_steps is None:
        straggler_lag_steps = knob_float("POLYAXON_TPU_STRAGGLER_LAG_STEPS")
    if heartbeat_fresh_s is None:
        heartbeat_fresh_s = knob_float("POLYAXON_TPU_STALL_HEARTBEAT_FRESH_S")
    out: Dict[str, Any] = {
        "stalled": False,
        "stall_age_s": 0.0,
        "stragglers": [],
        "progress": registry.get_progress(run_id),
    }
    rows = out["progress"]
    if not rows:
        return out
    newest = max(r["at"] for r in rows)
    age = now - newest
    hb = registry.last_heartbeat(run_id)
    if hb is not None and now - hb <= heartbeat_fresh_s and age > stall_after_s:
        out["stalled"] = True
        out["stall_age_s"] = age
    steps = [(r["process_id"], r["step"]) for r in rows if r["step"] is not None]
    if len(steps) >= 2:
        median_step = statistics.median(s for _, s in steps)
        for process_id, step in steps:
            lag = median_step - step
            if lag >= straggler_lag_steps:
                out["stragglers"].append(
                    {
                        "process_id": process_id,
                        "step": step,
                        "median_step": median_step,
                        "lag_steps": lag,
                    }
                )
    return out


def goodput_status(
    registry: RunRegistry,
    run_id: int,
    *,
    timeline_limit: int = 200,
) -> Dict[str, Any]:
    """Gang-wide goodput/MFU roll-up over ingested utilization rows.

    Pure read — shared by the watcher's gauge refresh and the API's
    ``/goodput`` endpoint and run-detail payload.

    Ledger rows are *cumulative per process*, so the latest row per
    process_id is that host's current truth; the gang aggregate sums
    FLOPs/tokens/buckets across those, takes the max per-process wall as
    the run's wall clock, and recomputes the ratios from the sums (so a
    straggling host drags the gang's goodput down, exactly as it drags
    the real run).  Empty until the first ledger row lands
    (``rows == 0``).
    """
    rows = registry.get_utilization(run_id)
    out: Dict[str, Any] = {
        "rows": len(rows),
        "processes": 0,
        "wall_s": 0.0,
        "buckets": {},
        "goodput_ratio": 0.0,
        "mfu": 0.0,
        "flops": 0.0,
        "tokens": 0,
        "steps": 0,
        "tokens_per_device_s": 0.0,
        "compile_s": 0.0,
        "compile_events": 0,
        "compile_cache_hits": 0,
        "compile_cache_misses": 0,
        "hbm_peak_bytes": 0.0,
        "kv_pool_bytes": 0.0,
        "spec_accept_rate": 0.0,
        "devices": 0,
        "device_kind": "",
        "final": False,
        "timeline": [],
    }
    if not rows:
        return out
    latest: Dict[Any, Dict[str, Any]] = {}
    for r in rows:
        latest[r["process_id"]] = r  # ingest order: last wins
    per_proc = list(latest.values())
    out["processes"] = len(per_proc)
    out["wall_s"] = max(r["wall_s"] or 0.0 for r in per_proc)
    out["flops"] = sum(r["flops"] or 0.0 for r in per_proc)
    out["tokens"] = sum(r["tokens"] or 0 for r in per_proc)
    out["steps"] = max(r["steps"] or 0 for r in per_proc)
    out["compile_s"] = sum(r["compile_s"] or 0.0 for r in per_proc)
    out["compile_events"] = sum(r["compile_events"] or 0 for r in per_proc)
    # Cache hit/miss counts ride the attrs JSON (the registry folds
    # unknown ledger-row keys there rather than growing the schema).
    out["compile_cache_hits"] = sum(
        int((r.get("attrs") or {}).get("compile_cache_hits") or 0)
        for r in per_proc
    )
    out["compile_cache_misses"] = sum(
        int((r.get("attrs") or {}).get("compile_cache_misses") or 0)
        for r in per_proc
    )
    out["hbm_peak_bytes"] = sum(r["hbm_peak_bytes"] or 0.0 for r in per_proc)
    # Serving engines report their KV block-pool bytes under the ledger's
    # free-form extras — summed here so /goodput HBM accounting sees a
    # quantized (int8) pool shrink gang-wide.
    out["kv_pool_bytes"] = sum(
        float(
            (((r.get("attrs") or {}).get("extra") or {}).get("kv_pool_bytes"))
            or 0.0
        )
        for r in per_proc
    )
    # Speculative-decoding acceptance, gang-wide: recomputed from the
    # summed proposed/accepted counters (a per-process rate average
    # would overweight idle replicas).
    def _extra(r, key):
        return float(
            (((r.get("attrs") or {}).get("extra") or {}).get(key)) or 0.0
        )

    proposed = sum(_extra(r, "spec_proposed_total") for r in per_proc)
    accepted = sum(_extra(r, "spec_accepted_total") for r in per_proc)
    out["spec_accept_rate"] = (
        round(accepted / proposed, 6) if proposed else 0.0
    )
    out["devices"] = sum(r["devices"] or 0 for r in per_proc)
    out["device_kind"] = next(
        (r["device_kind"] for r in per_proc if r["device_kind"]), ""
    )
    out["final"] = all(r["final"] for r in per_proc)
    buckets: Dict[str, Dict[str, float]] = {}
    for r in per_proc:
        for name, secs in (r["buckets"] or {}).items():
            secs = float(secs or 0.0)
            agg = buckets.setdefault(
                name, {"sum": 0.0, "min": secs, "max": secs}
            )
            agg["sum"] += secs
            agg["min"] = min(agg["min"], secs)
            agg["max"] = max(agg["max"], secs)
    out["buckets"] = buckets
    total_wall = sum(r["wall_s"] or 0.0 for r in per_proc)
    step_compute = buckets.get("step_compute_s", {}).get("sum", 0.0)
    if total_wall > 0:
        out["goodput_ratio"] = min(1.0, step_compute / total_wall)
    peak_total = sum(r["peak_flops_per_s"] or 0.0 for r in per_proc)
    if out["wall_s"] > 0 and peak_total > 0:
        out["mfu"] = out["flops"] / (out["wall_s"] * peak_total)
    if out["wall_s"] > 0 and out["devices"] > 0:
        out["tokens_per_device_s"] = out["tokens"] / (
            out["wall_s"] * out["devices"]
        )
    # MFU/goodput trajectory: every ingested row is a point (cumulative
    # averages, so the curve converges rather than jitters).
    # ``timeline_limit=0`` skips the timeline (run-detail wants the
    # roll-up only).
    for r in rows[-timeline_limit:] if timeline_limit > 0 else []:
        out["timeline"].append(
            {
                "at": r["created_at"],
                "process_id": r["process_id"],
                "mfu": r["mfu"] or 0.0,
                "goodput": r["goodput"] or 0.0,
                "wall_s": r["wall_s"] or 0.0,
            }
        )
    return out


class GangWatcher:
    """Stateless-per-call watcher; tail cursors live on the GangHandle."""

    def __init__(
        self,
        registry: RunRegistry,
        stats: Any = None,
        *,
        metrics: Any = None,
        max_poll_bytes: Optional[int] = None,
        stall_after_s: Optional[float] = None,
        straggler_lag_steps: Optional[float] = None,
        heartbeat_fresh_s: Optional[float] = None,
    ) -> None:
        self.registry = registry
        self.stats = stats
        # Optional MetricStore: per-run history series (run_mfu{run=...} etc.)
        # feeding the query API and the cross-run regression baselines.
        self.metrics = metrics
        self.max_poll_bytes = (
            max_poll_bytes
            if max_poll_bytes is not None
            else knob_int("POLYAXON_TPU_WATCHER_POLL_BYTES")
        )
        self.stall_after_s = (
            stall_after_s
            if stall_after_s is not None
            else knob_float("POLYAXON_TPU_STALL_AFTER_S")
        )
        self.straggler_lag_steps = (
            straggler_lag_steps
            if straggler_lag_steps is not None
            else knob_float("POLYAXON_TPU_STRAGGLER_LAG_STEPS")
        )
        self.heartbeat_fresh_s = (
            heartbeat_fresh_s
            if heartbeat_fresh_s is not None
            else knob_float("POLYAXON_TPU_STALL_HEARTBEAT_FRESH_S")
        )

    # -- report ingestion -----------------------------------------------------
    def ingest(self, handle: GangHandle) -> None:
        """Drain new report lines from every gang process into the registry."""
        # Ingest-lag watermark: the newest report line's own wall time
        # ("at" for progress beats, "ts" otherwise).  now - watermark is
        # how far this gang's telemetry lags reality — the control plane's
        # single best saturation signal (a healthy watcher keeps it near
        # the workers' emit cadence; a saturated one falls behind even
        # though every poll "succeeds").
        newest = float(getattr(handle, "ingest_newest_at", 0.0) or 0.0)
        for process_id in range(handle.plan.num_hosts):
            path = handle.paths.report_file(process_id)
            if not path.exists():
                continue
            offset = handle.report_offsets.get(process_id, 0)
            with open(path, "rb") as fh:
                fh.seek(offset)
                # Bounded read: a long catch-up (control-plane restart, slow
                # poll cadence) drains in max_poll_bytes slices across polls
                # instead of one unbounded slurp; the durable offset carries
                # the remainder.
                chunk = fh.read(self.max_poll_bytes)
            if not chunk:
                continue
            # Only consume complete lines; a partially-flushed tail is
            # re-read next poll.
            end = chunk.rfind(b"\n")
            if end < 0:
                if len(chunk) >= self.max_poll_bytes:
                    # A single line larger than the whole poll budget can
                    # never terminate inside a bounded read — skip these
                    # bytes or the tail wedges forever.  The line's final
                    # fragment (up to its real newline) will fail to parse
                    # next poll and be skipped like any malformed line.
                    logger.warning(
                        "Oversized report line from proc %d (> %d bytes); skipping",
                        process_id,
                        self.max_poll_bytes,
                    )
                    handle.report_offsets[process_id] = offset + len(chunk)
                    self.registry.set_report_offset(
                        handle.run_id, process_id, offset + len(chunk)
                    )
                continue
            handle.report_offsets[process_id] = offset + end + 1
            for raw in chunk[: end + 1].splitlines():
                try:
                    event = json.loads(raw)
                except json.JSONDecodeError:
                    logger.warning("Bad report line from proc %d: %r", process_id, raw[:200])
                    continue
                if not isinstance(event, dict):
                    # json.loads accepts bare scalars/arrays ("123" → int);
                    # those are junk on this channel, not a poll-aborting
                    # error.
                    logger.warning(
                        "Non-object report line from proc %d: %r",
                        process_id,
                        raw[:200],
                    )
                    continue
                try:
                    self._apply(handle, process_id, event)
                except Exception:
                    # One poisonous line (bad field types, etc.) must not
                    # permanently wedge the tail behind it.
                    logger.warning(
                        "Failed to apply report line from proc %d: %r",
                        process_id,
                        raw[:200],
                        exc_info=True,
                    )
                else:
                    at = event.get("at") or event.get("ts")
                    if isinstance(at, (int, float)) and at > newest:
                        newest = float(at)
            # Durable cursor: a restarted control plane reattaches and
            # resumes the tail here. Persisted AFTER the apply loop — a
            # crash in between replays these lines (status upserts are
            # idempotent, metrics at-least-once) instead of silently
            # skipping a worker's terminal status.
            self.registry.set_report_offset(
                handle.run_id, process_id, offset + end + 1
            )
        if newest:
            try:
                handle.ingest_newest_at = newest
            except Exception:  # frozen test stand-ins: no lag tracking
                pass

    def _apply(self, handle: GangHandle, process_id: int, event: dict) -> None:
        etype = event.get("type")
        run_id = handle.run_id
        if etype in ("metric", "resources"):
            self.registry.add_metric(run_id, event.get("values") or {}, step=event.get("step"))
        elif etype == "log":
            self.registry.add_log(run_id, event.get("line", ""), process_id=process_id)
        elif etype == "span":
            self.registry.add_span(run_id, event, process_id=process_id)
        elif etype == "ledger":
            self.registry.add_utilization(run_id, event, process_id=process_id)
        elif etype == "heartbeat":
            self.registry.ping_heartbeat(run_id, at=event.get("ts"))
        elif etype == "progress":
            self.registry.upsert_progress(
                run_id,
                process_id,
                step=event.get("step"),
                epoch=event.get("epoch"),
                throughput=event.get("throughput"),
                # "at" = the beat's own wall time; emission is throttled, so
                # the line's ts can postdate the progress it describes.
                at=event.get("at") or event.get("ts"),
            )
        elif etype == "anomaly":
            attrs = {
                k: v
                for k, v in event.items()
                if k not in ("type", "ts", "kind", "message")
            }
            self.registry.add_anomaly(
                run_id,
                event.get("kind") or "anomaly",
                process_id=process_id,
                message=event.get("message"),
                attrs=attrs,
                created_at=event.get("ts"),
            )
        elif etype == "command":
            # A worker's per-process lifecycle state for a bus command
            # (acked/complete/failed) — folded into the command roll-up.
            uuid = event.get("uuid")
            state = event.get("state")
            if not uuid or not state:
                logger.warning(
                    "Command report without uuid/state from proc %d", process_id
                )
                return
            # Handler result data (e.g. checkpoint-now's saved step) rides
            # the same line as extra keys → into the command's ack attrs.
            extra = {
                k: v
                for k, v in event.items()
                if k not in ("type", "ts", "uuid", "state", "message")
                and v is not None
            }
            self.registry.mark_command(
                str(uuid),
                process_id,
                str(state),
                message=event.get("message"),
                attrs=extra or None,
            )
        elif etype == "capture":
            # On-demand profiling record: one latest-wins row per
            # (capture, host).  A torn/partial record (no capture_id) is a
            # malformed line, not a poll-fatal error.
            capture_id = event.get("capture_id")
            if not capture_id:
                logger.warning(
                    "Capture report without capture_id from proc %d", process_id
                )
                return
            artifacts = event.get("artifacts")
            self.registry.upsert_capture(
                run_id,
                str(capture_id),
                process_id,
                status=event.get("status"),
                start_step=event.get("start_step"),
                num_steps=event.get("num_steps"),
                started_at=event.get("started_at"),
                finished_at=event.get("finished_at"),
                artifacts=list(artifacts) if artifacts else None,
                message=event.get("message"),
                attrs=event.get("attrs") or None,
            )
            if self.stats is not None and event.get("status") in (
                "complete",
                "failed",
            ):
                self.stats.incr("profile_captures")
        elif etype == "service":
            # A service refining its own URL (jupyter appends its token
            # as a query string; an absolute url replaces outright).
            url = event.get("url")
            if not url and event.get("query"):
                base = self.registry.get_run(run_id).service_url
                if base:
                    sep = "&" if "?" in base else "?"
                    url = f"{base}{sep}{event['query']}"
            if url:
                self.registry.update_run(run_id, service_url=url)
        elif etype == "status":
            status = event.get("status")
            if not status:
                logger.warning("Status report without status from proc %d", process_id)
                return
            message = event.get("message")
            if event.get("traceback"):
                self.registry.add_log(run_id, event["traceback"], process_id=process_id)
            self.registry.upsert_process(run_id, process_id, status=status)
            if message:
                self.registry.add_log(
                    run_id, f"[proc {process_id}] {status}: {message}", process_id=process_id
                )
        else:
            # Version skew (a newer worker's line kind against an older
            # control plane) is skip-and-warn, never poll-fatal.
            logger.warning(
                "Unknown report line type %r from proc %d; skipping",
                etype,
                process_id,
            )

    # -- liveness reconcile ---------------------------------------------------
    def reconcile(self, handle: GangHandle) -> List[str]:
        """Reconcile subprocess exit codes with reported statuses.

        A process that exited without reporting a terminal status (crash,
        OOM-kill) is recorded from its exit code — the reference's sidecar
        reconcile for pods that die before phoning home.
        """
        reported = {p["process_id"]: p for p in self.registry.get_processes(handle.run_id)}
        statuses: List[str] = []
        for process_id, exit_code in handle.poll().items():
            rec = reported.get(process_id)
            status = rec["status"] if rec else S.STARTING
            job_done = status in (S.SUCCEEDED, S.FAILED, S.STOPPED)
            if exit_code is not None and not job_done:
                status = S.SUCCEEDED if exit_code == 0 else S.FAILED
                self.registry.upsert_process(
                    handle.run_id, process_id, status=status, exit_code=exit_code
                )
            elif exit_code is not None and rec is not None and rec.get("exit_code") is None:
                self.registry.upsert_process(
                    handle.run_id, process_id, status=status, exit_code=exit_code
                )
            statuses.append(status)
        return statuses

    # -- gang-level anomaly detection -----------------------------------------
    def detect_anomalies(
        self, handle: GangHandle, *, now: Optional[float] = None
    ) -> Dict[str, Any]:
        """Flag gang-wide stalls and stragglers; persist each *transition*.

        Edge-triggered: one ``stall``/``straggler`` anomaly row per episode
        (per-handle marks de-dupe across monitor ticks; recovery re-arms),
        so the anomalies table reads as an incident timeline rather than a
        row per 200ms poll.  Gauges (``run_stall_age_s`` /
        ``straggler_lag_steps``) track the *current* state on the stats
        backend and recover to 0.
        """
        now = now if now is not None else time.time()
        status = anomaly_status(
            self.registry,
            handle.run_id,
            now=now,
            stall_after_s=self.stall_after_s,
            straggler_lag_steps=self.straggler_lag_steps,
            heartbeat_fresh_s=self.heartbeat_fresh_s,
        )
        marks = getattr(handle, "anomaly_marks", None)
        if marks is None:
            marks = {}
            try:
                handle.anomaly_marks = marks
            except Exception:  # frozen test stand-ins: detection, no dedup
                pass
        if status["stalled"]:
            if not marks.get("stall"):
                marks["stall"] = True
                steps = [r["step"] for r in status["progress"]]
                self.registry.add_anomaly(
                    handle.run_id,
                    "stall",
                    message=(
                        f"gang alive but no progress for "
                        f"{status['stall_age_s']:.1f}s (steps: {steps})"
                    ),
                    attrs={
                        "age_s": status["stall_age_s"],
                        "threshold_s": self.stall_after_s,
                        "steps": steps,
                    },
                    created_at=now,
                )
        else:
            marks["stall"] = False
        lagging = {s["process_id"]: s for s in status["stragglers"]}
        for process_id, info in lagging.items():
            key = f"straggler:{process_id}"
            if not marks.get(key):
                marks[key] = True
                self.registry.add_anomaly(
                    handle.run_id,
                    "straggler",
                    process_id=process_id,
                    message=(
                        f"proc {process_id} at step {info['step']}, "
                        f"{info['lag_steps']:.0f} steps behind the gang "
                        f"median ({info['median_step']})"
                    ),
                    attrs={
                        "lag_steps": info["lag_steps"],
                        "median_step": info["median_step"],
                        "threshold_steps": self.straggler_lag_steps,
                    },
                    created_at=now,
                )
        for key in list(marks):
            if key.startswith("straggler:") and int(key.split(":")[1]) not in lagging:
                marks[key] = False
        if self.stats is not None:
            self.stats.gauge("run_stall_age_s", float(status["stall_age_s"]))
            worst = max((s["lag_steps"] for s in status["stragglers"]), default=0.0)
            self.stats.gauge("straggler_lag_steps", float(worst))
        return status

    # -- goodput gauges --------------------------------------------------------
    def _refresh_goodput_gauges(self, handle: GangHandle) -> None:
        """Publish the gang's current goodput/MFU roll-up as gauges.

        No-op until the first ledger row lands — the gauges should show
        the last real measurement, never a synthetic zero."""
        if self.stats is None and self.metrics is None:
            return
        try:
            status = goodput_status(self.registry, handle.run_id)
        except Exception:
            logger.warning(
                "Goodput roll-up failed for run %d", handle.run_id, exc_info=True
            )
            return
        if not status["rows"]:
            return
        if self.stats is not None:
            self.stats.gauge("run_goodput_ratio", float(status["goodput_ratio"]))
            self.stats.gauge("run_mfu", float(status["mfu"]))
            self.stats.gauge("run_compile_s_total", float(status["compile_s"]))
            self.stats.gauge("run_hbm_peak_bytes", float(status["hbm_peak_bytes"]))
        if self.metrics is not None:
            # Run-labeled history series: these are what the query API serves
            # per run and what fold_run_baselines summarises at completion.
            at = time.time()
            run = handle.run_id
            for series, field in (
                ("run_mfu", "mfu"),
                ("run_goodput_ratio", "goodput_ratio"),
                ("run_tokens_per_device_s", "tokens_per_device_s"),
                ("run_spec_accept_rate", "spec_accept_rate"),
            ):
                self.metrics.record(
                    labeled_key(series, run=run), float(status[field]), at
                )

    def _refresh_command_gauges(self, handle: GangHandle) -> None:
        """``profile_capture_active``: profile commands still in flight
        (pending/acked) on this gang — pairs with the
        ``profile_captures`` counter the ingest path increments."""
        if self.stats is None:
            return
        try:
            cmds = self.registry.get_commands(handle.run_id, kind="profile")
        except Exception:
            logger.warning(
                "Command roll-up failed for run %d", handle.run_id, exc_info=True
            )
            return
        active = sum(1 for c in cmds if c["status"] in ("pending", "acked"))
        self.stats.gauge("profile_capture_active", float(active))

    # -- ingest lag -------------------------------------------------------------
    def _record_ingest_lag(
        self, handle: GangHandle, *, terminal: bool, now: Optional[float] = None
    ) -> None:
        """Export how far this gang's report ingest lags the lines' own
        wall times (watermark kept by :meth:`ingest`).

        Per-run gauge ``watcher_ingest_lag_run_s{run=...}`` follows the
        alarm-gauge discipline (recovers to 0 once the run goes terminal —
        a finished run has nothing left to lag behind); the fleet-wide
        ``watcher_ingest_lag_s`` histogram accumulates one sample per
        live-run poll, so its p99 is the saturation-bench gate.
        """
        if self.stats is None:
            return
        key = labeled_key("watcher_ingest_lag_run_s", run=handle.run_id)
        if terminal:
            # Zero only the runs whose gauge was actually exported.
            if getattr(handle, "ingest_lag_live", False):
                self.stats.gauge(key, 0.0)
                try:
                    handle.ingest_lag_live = False
                except Exception:
                    pass
            return
        newest = float(getattr(handle, "ingest_newest_at", 0.0) or 0.0)
        if not newest:
            return  # no timestamped line ingested yet — nothing to lag
        now = now if now is not None else time.time()
        lag = max(0.0, now - newest)
        self.stats.gauge(key, lag)
        self.stats.observe("watcher_ingest_lag_s", lag)
        try:
            handle.ingest_lag_live = True
        except Exception:  # frozen test stand-ins: export without recovery
            pass

    def observe(self, handle: GangHandle) -> Optional[str]:
        """One poll: ingest reports, reconcile liveness, return gang roll-up."""
        tracer = get_tracer()
        # Polls are frequent (per-run monitor interval) — sample like a
        # hot-path span; control-plane spans stay in the ring buffer.
        with tracer.span(
            "watcher.observe", sample=tracer.hot_sample, run_id=handle.run_id
        ):
            self.ingest(handle)
            statuses = self.reconcile(handle)
            rollup = gang_status(statuses)
            self._record_ingest_lag(handle, terminal=rollup != S.RUNNING)
            if rollup == S.RUNNING:
                # Only live gangs can stall; a finished gang's progress rows
                # age out harmlessly.
                try:
                    self.detect_anomalies(handle)
                except Exception:
                    logger.warning(
                        "Anomaly detection failed for run %d",
                        handle.run_id,
                        exc_info=True,
                    )
                self._refresh_goodput_gauges(handle)
                self._refresh_command_gauges(handle)
            elif self.stats is not None:
                # A run that goes terminal mid-episode must not pin the
                # alarm gauges at its last stalled value.
                marks = getattr(handle, "anomaly_marks", None)
                if marks and any(marks.values()):
                    self.stats.gauge("run_stall_age_s", 0.0)
                    self.stats.gauge("straggler_lag_steps", 0.0)
                    marks.clear()
                # Unlike the alarm gauges, goodput/MFU *freeze* at the
                # run's final truth: one last refresh picks up the final
                # ledger rows ingested this same poll, then stops — the
                # gauges keep reporting what the run achieved.
                if not getattr(handle, "goodput_frozen", False):
                    # In-flight profile commands expire with the run (see
                    # _record_done) — the gauge must not stay pinned.
                    self.stats.gauge("profile_capture_active", 0.0)
                    self._refresh_goodput_gauges(handle)
                    try:
                        handle.goodput_frozen = True
                    except Exception:
                        pass
            return rollup
