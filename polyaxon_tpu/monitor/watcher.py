"""Gang observation: tail report files, reconcile process liveness.

Parity: the reference's observation stack — the ocular pod watch loop
(``monitor_statuses/monitor.py:87-200``), the k8s events handlers writing
job-status rows (``k8s_events_handlers/tasks/statuses.py:36-288``), and the
sidecar liveness reconcile (``sidecar/sidecar/__main__.py:39-58``).
TPU-native: statuses/metrics/logs arrive as appended JSON lines in the run's
``reports/`` dir; liveness is the subprocess table itself.  Both sources are
reconciled into the registry, statuses gated by the job lifecycle, and the
gang roll-up (``gang_status``) becomes the experiment status.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional

from polyaxon_tpu.db.registry import RunRegistry
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.lifecycles.registry import gang_status
from polyaxon_tpu.spawner.local import GangHandle
from polyaxon_tpu.tracking.trace import get_tracer

logger = logging.getLogger(__name__)


class GangWatcher:
    """Stateless-per-call watcher; tail cursors live on the GangHandle."""

    def __init__(self, registry: RunRegistry) -> None:
        self.registry = registry

    # -- report ingestion -----------------------------------------------------
    def ingest(self, handle: GangHandle) -> None:
        """Drain new report lines from every gang process into the registry."""
        for process_id in range(handle.plan.num_hosts):
            path = handle.paths.report_file(process_id)
            if not path.exists():
                continue
            offset = handle.report_offsets.get(process_id, 0)
            with open(path, "rb") as fh:
                fh.seek(offset)
                chunk = fh.read()
            if not chunk:
                continue
            # Only consume complete lines; a partially-flushed tail is
            # re-read next poll.
            end = chunk.rfind(b"\n")
            if end < 0:
                continue
            handle.report_offsets[process_id] = offset + end + 1
            for raw in chunk[: end + 1].splitlines():
                try:
                    event = json.loads(raw)
                except json.JSONDecodeError:
                    logger.warning("Bad report line from proc %d: %r", process_id, raw[:200])
                    continue
                self._apply(handle, process_id, event)
            # Durable cursor: a restarted control plane reattaches and
            # resumes the tail here. Persisted AFTER the apply loop — a
            # crash in between replays these lines (status upserts are
            # idempotent, metrics at-least-once) instead of silently
            # skipping a worker's terminal status.
            self.registry.set_report_offset(
                handle.run_id, process_id, offset + end + 1
            )

    def _apply(self, handle: GangHandle, process_id: int, event: dict) -> None:
        etype = event.get("type")
        run_id = handle.run_id
        if etype in ("metric", "resources"):
            self.registry.add_metric(run_id, event.get("values") or {}, step=event.get("step"))
        elif etype == "log":
            self.registry.add_log(run_id, event.get("line", ""), process_id=process_id)
        elif etype == "span":
            self.registry.add_span(run_id, event, process_id=process_id)
        elif etype == "heartbeat":
            self.registry.ping_heartbeat(run_id, at=event.get("ts"))
        elif etype == "service":
            # A service refining its own URL (jupyter appends its token
            # as a query string; an absolute url replaces outright).
            url = event.get("url")
            if not url and event.get("query"):
                base = self.registry.get_run(run_id).service_url
                if base:
                    sep = "&" if "?" in base else "?"
                    url = f"{base}{sep}{event['query']}"
            if url:
                self.registry.update_run(run_id, service_url=url)
        elif etype == "status":
            status = event.get("status")
            if not status:
                logger.warning("Status report without status from proc %d", process_id)
                return
            message = event.get("message")
            if event.get("traceback"):
                self.registry.add_log(run_id, event["traceback"], process_id=process_id)
            self.registry.upsert_process(run_id, process_id, status=status)
            if message:
                self.registry.add_log(
                    run_id, f"[proc {process_id}] {status}: {message}", process_id=process_id
                )

    # -- liveness reconcile ---------------------------------------------------
    def reconcile(self, handle: GangHandle) -> List[str]:
        """Reconcile subprocess exit codes with reported statuses.

        A process that exited without reporting a terminal status (crash,
        OOM-kill) is recorded from its exit code — the reference's sidecar
        reconcile for pods that die before phoning home.
        """
        reported = {p["process_id"]: p for p in self.registry.get_processes(handle.run_id)}
        statuses: List[str] = []
        for process_id, exit_code in handle.poll().items():
            rec = reported.get(process_id)
            status = rec["status"] if rec else S.STARTING
            job_done = status in (S.SUCCEEDED, S.FAILED, S.STOPPED)
            if exit_code is not None and not job_done:
                status = S.SUCCEEDED if exit_code == 0 else S.FAILED
                self.registry.upsert_process(
                    handle.run_id, process_id, status=status, exit_code=exit_code
                )
            elif exit_code is not None and rec is not None and rec.get("exit_code") is None:
                self.registry.upsert_process(
                    handle.run_id, process_id, status=status, exit_code=exit_code
                )
            statuses.append(status)
        return statuses

    def observe(self, handle: GangHandle) -> Optional[str]:
        """One poll: ingest reports, reconcile liveness, return gang roll-up."""
        tracer = get_tracer()
        # Polls are frequent (per-run monitor interval) — sample like a
        # hot-path span; control-plane spans stay in the ring buffer.
        with tracer.span(
            "watcher:observe", sample=tracer.hot_sample, run_id=handle.run_id
        ):
            self.ingest(handle)
            statuses = self.reconcile(handle)
            return gang_status(statuses)
