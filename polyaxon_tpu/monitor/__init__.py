from polyaxon_tpu.monitor.watcher import GangWatcher

__all__ = ["GangWatcher"]
