from polyaxon_tpu.monitor.alerts import AlertEngine
from polyaxon_tpu.monitor.remediation import RemediationEngine
from polyaxon_tpu.monitor.watcher import GangWatcher

__all__ = ["AlertEngine", "GangWatcher", "RemediationEngine"]
