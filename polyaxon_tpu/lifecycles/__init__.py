from polyaxon_tpu.lifecycles.machine import LifeCycle, StatusOptions
from polyaxon_tpu.lifecycles.registry import (
    ExperimentLifeCycle,
    GroupLifeCycle,
    JobLifeCycle,
    OperationRunLifeCycle,
    PipelineLifeCycle,
    lifecycle_for_kind,
)

__all__ = [
    "LifeCycle",
    "StatusOptions",
    "ExperimentLifeCycle",
    "GroupLifeCycle",
    "JobLifeCycle",
    "PipelineLifeCycle",
    "OperationRunLifeCycle",
    "lifecycle_for_kind",
]
