"""Per-entity lifecycle instances + gang status roll-up.

Parity targets in the reference:
- ``polyaxon/lifecycles/experiments.py:10-62`` (experiment machine + the
  ``jobs_status`` roll-up used when aggregating per-replica pod statuses),
- ``polyaxon/lifecycles/jobs.py`` (job machine),
- ``polyaxon/lifecycles/experiment_groups.py`` (group machine),
- ``polyaxon/lifecycles/pipelines.py`` + ``operations.py`` (DAG machines).

Here a distributed experiment's per-*host-process* statuses roll up to the
experiment status with gang semantics: any failure fails the gang (jax
collectives are all-or-nothing over ICI/DCN, unlike the reference's PS
clusters where a lost PS might only degrade).
"""

from __future__ import annotations

from typing import List, Optional

from polyaxon_tpu.lifecycles.machine import LifeCycle, StatusOptions

S = StatusOptions

#: Experiments: full machine incl. QUEUED (dispatched into the build→start
#: chain or awaiting device admission), BUILDING (code snapshot), RESUMING.
ExperimentLifeCycle = LifeCycle(
    pending=(S.CREATED, S.RESUMING),
    preparing=(S.QUEUED, S.BUILDING),
    running=(S.SCHEDULED, S.STARTING, S.RUNNING, S.STOPPING),
    done=(S.SUCCEEDED, S.FAILED, S.UPSTREAM_FAILED, S.STOPPED, S.SKIPPED),
    transient=(S.WARNING, S.UNKNOWN, S.UNSCHEDULABLE),
    resumable_from=(S.SUCCEEDED, S.STOPPED, S.SKIPPED, S.WARNING, S.FAILED),
    # A BUILT run can still queue at device admission (QUEUED otherwise
    # precedes BUILDING in the preparing order and would be unreachable,
    # stranding built runs when every slice is held).
    extra_edges={S.QUEUED: (S.BUILDING,)},
)

#: Host-process jobs (the replica unit inside a gang).
JobLifeCycle = LifeCycle(
    pending=(S.CREATED,),
    preparing=(S.BUILDING,),
    running=(S.SCHEDULED, S.STARTING, S.RUNNING, S.STOPPING),
    done=(S.SUCCEEDED, S.FAILED, S.UPSTREAM_FAILED, S.STOPPED, S.SKIPPED),
    transient=(S.WARNING, S.UNKNOWN, S.UNSCHEDULABLE),
)

#: Experiment groups (hpsearch sweeps): RUNNING covers the whole sweep window.
GroupLifeCycle = LifeCycle(
    pending=(S.CREATED, S.RESUMING),
    running=(S.RUNNING,),
    done=(S.SUCCEEDED, S.FAILED, S.STOPPED, S.SKIPPED, S.DONE),
    transient=(S.WARNING,),
    resumable_from=(S.DONE, S.STOPPED, S.SUCCEEDED),
)

#: Workflow pipelines and their operation runs (polyflow equivalent).
PipelineLifeCycle = LifeCycle(
    pending=(S.CREATED, S.RESUMING),
    preparing=(S.SCHEDULED,),
    running=(S.RUNNING,),
    done=(S.SUCCEEDED, S.FAILED, S.UPSTREAM_FAILED, S.STOPPED, S.SKIPPED, S.DONE),
    transient=(S.WARNING,),
    resumable_from=(S.DONE, S.STOPPED),
)

OperationRunLifeCycle = LifeCycle(
    pending=(S.CREATED, S.RETRYING),
    preparing=(S.SCHEDULED,),
    running=(S.RUNNING,),
    done=(S.SUCCEEDED, S.FAILED, S.UPSTREAM_FAILED, S.STOPPED, S.SKIPPED),
    transient=(S.WARNING,),
    resumable_from=(S.FAILED, S.STOPPED),
)

_KIND_MAP = {
    "experiment": ExperimentLifeCycle,
    "job": JobLifeCycle,
    "build": JobLifeCycle,
    "notebook": JobLifeCycle,
    "tensorboard": JobLifeCycle,
    "service": JobLifeCycle,
    "group": GroupLifeCycle,
    "pipeline": PipelineLifeCycle,
    "operation": OperationRunLifeCycle,
}


def lifecycle_for_kind(kind: str) -> LifeCycle:
    try:
        return _KIND_MAP[kind]
    except KeyError:
        raise KeyError(f"No lifecycle registered for kind {kind!r}") from None


def gang_status(process_statuses: List[str]) -> Optional[str]:
    """Roll a gang's per-process statuses up to one experiment status.

    Gang semantics (vs reference ``ExperimentLifeCycle.jobs_status``,
    ``lifecycles/experiments.py:121-147``): a jax.distributed world is
    all-or-nothing — one failed process fails the experiment even while
    others still run, and success requires *all* processes succeeded.
    """
    if not process_statuses:
        return None
    statuses = set(process_statuses)
    if S.UNKNOWN in statuses:
        return S.UNKNOWN
    if S.UNSCHEDULABLE in statuses:
        return S.UNSCHEDULABLE
    if S.FAILED in statuses or S.UPSTREAM_FAILED in statuses:
        return S.FAILED
    if S.STOPPED in statuses:
        return S.STOPPED
    if S.STOPPING in statuses:
        # Still live: the stop may fail; only STOPPED is terminal.
        return S.STOPPING
    if S.WARNING in statuses:
        return S.WARNING
    done = {S.SUCCEEDED, S.SKIPPED}
    if statuses <= done:
        # All processes finished cleanly; a mixed succeeded/skipped gang
        # counts as succeeded (skip only wins when unanimous).
        return S.SUCCEEDED if S.SUCCEEDED in statuses else S.SKIPPED
    if S.RUNNING in statuses or (statuses & done):
        # Any process running — or some done while others still progress.
        return S.RUNNING
    if S.STARTING in statuses or S.SCHEDULED in statuses or S.BUILDING in statuses:
        return S.STARTING
    if statuses <= {S.CREATED, S.RESUMING}:
        # Freshly created gang: pending, not unknown (the reference folds
        # CREATED into its starting phase — jobs.py STARTING_STATUS).
        return S.CREATED
    return S.UNKNOWN
