"""Declarative status state machines.

Capability parity with the reference's per-entity lifecycle classes
(``polyaxon/lifecycles/{statuses,experiments,jobs,experiment_groups,
pipelines}.py`` — transition matrices gating every status write, checked by
e.g. ``scheduler/tasks/experiments.py:72-77``). The design here is different:
instead of hand-written transition matrices per entity, a ``LifeCycle`` is
built from a compact *phase* taxonomy (pending → preparing → running → done)
plus per-entity overrides, and the matrix is derived.  Statuses are plain
strings so they serialize straight into the registry and over the wire.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, Mapping, Optional, Set


class StatusOptions:
    """Canonical status vocabulary (shared with the reference for parity)."""

    CREATED = "created"
    RESUMING = "resuming"
    QUEUED = "queued"
    BUILDING = "building"
    SCHEDULED = "scheduled"
    UNSCHEDULABLE = "unschedulable"
    STARTING = "starting"
    RUNNING = "running"
    WARNING = "warning"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    UPSTREAM_FAILED = "upstream_failed"
    STOPPING = "stopping"
    STOPPED = "stopped"
    SKIPPED = "skipped"
    RETRYING = "retrying"
    UNKNOWN = "unknown"
    DONE = "done"


class LifeCycle:
    """A status state machine with transition gating.

    ``can_transition(frm, to)`` is the single write-gate every status mutation
    must pass (the registry enforces it).  The machine is derived from four
    ordered phase sets; a transition is legal when it does not leave a done
    state (done states are terminal except explicit resume edges) and does not
    move "backwards" into creation.
    """

    def __init__(
        self,
        *,
        pending: Iterable[str],
        preparing: Iterable[str] = (),
        running: Iterable[str],
        done: Iterable[str],
        transient: Iterable[str] = (StatusOptions.WARNING, StatusOptions.UNKNOWN),
        failed: Iterable[str] = (StatusOptions.FAILED, StatusOptions.UPSTREAM_FAILED),
        resumable_from: Iterable[str] = (),
        resume_statuses: Iterable[str] = (StatusOptions.RESUMING, StatusOptions.RETRYING),
        heartbeat: Iterable[str] = (StatusOptions.RUNNING,),
        extra_edges: Optional[Mapping[str, Iterable[str]]] = None,
    ) -> None:
        self._preparing_order = tuple(preparing)
        self._running_order = tuple(running)
        #: Pending statuses acting as explicit resume entry points: reachable
        #: only from ``resumable_from`` (never from nothing); every other
        #: pending status is reachable only at creation time (from ``None``).
        self._resume_statuses = tuple(resume_statuses)
        self.PENDING_STATUS: FrozenSet[str] = frozenset(pending)
        self.PREPARING_STATUS: FrozenSet[str] = frozenset(self._preparing_order)
        self.RUNNING_STATUS: FrozenSet[str] = frozenset(self._running_order)
        self.DONE_STATUS: FrozenSet[str] = frozenset(done)
        self.TRANSIENT_STATUS: FrozenSet[str] = frozenset(transient)
        self.FAILED_STATUS: FrozenSet[str] = frozenset(failed) & self.DONE_STATUS
        self.HEARTBEAT_STATUS: FrozenSet[str] = frozenset(heartbeat)
        self.VALUES: FrozenSet[str] = (
            self.PENDING_STATUS
            | self.PREPARING_STATUS
            | self.RUNNING_STATUS
            | self.DONE_STATUS
            | self.TRANSIENT_STATUS
        )
        self._matrix = self._derive_matrix(resumable_from, extra_edges or {})

    # -- matrix derivation ---------------------------------------------------
    def _derive_matrix(
        self,
        resumable_from: Iterable[str],
        extra_edges: Mapping[str, Iterable[str]],
    ) -> Dict[str, Set[str]]:
        live = self.VALUES - self.DONE_STATUS
        matrix: Dict[str, Set[str]] = {}
        # Entry states are reachable only at creation time (from nothing);
        # resume states only via their explicit resume edges (the reference
        # routes resume through RESUMING the same way —
        # lifecycles/experiments.py TRANSITION_MATRIX: CREATED: {None}).
        resume_members = self.PENDING_STATUS & set(self._resume_statuses)
        for status in self.PENDING_STATUS:
            if status in resume_members:
                matrix[status] = set(resumable_from)
            else:
                matrix[status] = {None}  # type: ignore[arg-type]
        # Forward motion only: a preparing/running state is reachable from any
        # earlier live phase, from transient states, and from *earlier*
        # statuses within its own phase (phase tuples are ordered, e.g.
        # scheduled → starting → running; backward moves are illegal).
        seen_earlier: Set[str] = set(self.PENDING_STATUS)
        for phase_order in (self._preparing_order, self._running_order):
            phase_seen: Set[str] = set()
            for status in phase_order:
                matrix[status] = set(seen_earlier) | set(self.TRANSIENT_STATUS) | phase_seen
                phase_seen.add(status)
            seen_earlier |= set(phase_order)
        # Done states absorb everything live.
        for status in self.DONE_STATUS:
            matrix[status] = set(live)
        # Stop may also override other done states except itself/skipped (the
        # reference allows re-stopping failed/succeeded runs for cleanup).
        if StatusOptions.STOPPED in self.DONE_STATUS:
            matrix[StatusOptions.STOPPED] = set(
                self.VALUES - {StatusOptions.STOPPED, StatusOptions.SKIPPED}
            )
        # Transient states are reachable from anything live (not from done,
        # and never from themselves).
        for status in self.TRANSIENT_STATUS:
            matrix[status] = set(live - {status})
        for status, sources in extra_edges.items():
            matrix.setdefault(status, set()).update(sources)
        return matrix

    @property
    def transition_matrix(self) -> Mapping[str, Set[str]]:
        return self._matrix

    # -- gates ---------------------------------------------------------------
    def can_transition(self, status_from: Optional[str], status_to: str) -> bool:
        if status_to not in self._matrix:
            return False
        return status_from in self._matrix[status_to]

    # -- predicates ----------------------------------------------------------
    def is_pending(self, status: str) -> bool:
        return status in self.PENDING_STATUS

    def is_running(self, status: str) -> bool:
        return status in self.RUNNING_STATUS or status in self.PREPARING_STATUS

    def is_done(self, status: str) -> bool:
        return status in self.DONE_STATUS

    def failed(self, status: str) -> bool:
        return status in self.FAILED_STATUS

    def succeeded(self, status: str) -> bool:
        return status == StatusOptions.SUCCEEDED

    def stopped(self, status: str) -> bool:
        return status == StatusOptions.STOPPED

    def skipped(self, status: str) -> bool:
        return status == StatusOptions.SKIPPED

    def is_unschedulable(self, status: str) -> bool:
        return status == StatusOptions.UNSCHEDULABLE

    def is_warning(self, status: str) -> bool:
        return status == StatusOptions.WARNING

    def is_unknown(self, status: str) -> bool:
        return status == StatusOptions.UNKNOWN

    def is_stoppable(self, status: str) -> bool:
        return not self.is_done(status)

    def needs_heartbeat(self, status: str) -> bool:
        return status in self.HEARTBEAT_STATUS
