from polyaxon_tpu.auditor.service import Auditor

__all__ = ["Auditor"]
