"""Central event recorder + fan-out.

Parity: reference ``auditor/service.py:33-58`` — ``record(event_type, ...)``
serializes the event, persists it (activitylogs/tracker), and fans out to
the executor and notifier.  Here the celery indirection is gone: handlers
are plain callables invoked inline, in registration order; the executor's
follow-up *actions* still go through the task bus so they get countdown /
retry semantics.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional

from polyaxon_tpu.db.registry import RunRegistry
from polyaxon_tpu.events import Event

logger = logging.getLogger(__name__)

Handler = Callable[[Event], None]


class Auditor:
    def __init__(self, registry: Optional[RunRegistry] = None) -> None:
        self.registry = registry
        self._handlers: List[Handler] = []

    def subscribe(self, handler: Handler) -> None:
        self._handlers.append(handler)

    def record(self, event_type: str, **context: Any) -> Event:
        event = Event(event_type=event_type, context=context)
        if self.registry is not None:
            self.registry.record_activity(event.event_type, event.context)
        for handler in self._handlers:
            try:
                handler(event)
            except Exception:  # noqa: BLE001 — an observer must not break the producer
                logger.exception("Event handler failed for %s", event_type)
        return event
