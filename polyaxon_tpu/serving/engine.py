"""Continuous-batching generation engine over a PAGED KV cache.

Iteration-level scheduling (Orca) over block-table KV management
(vLLM's PagedAttention) with Sarathi-style chunked prefill: the engine
owns one ``[L, num_blocks, block_size, Hkv, d]`` block POOL and ONE
jitted :func:`~polyaxon_tpu.models.decode.paged_decode_step` whose
shapes depend only on (slots, pool size, table width) — per-slot block
tables, positions, and the active mask are DATA, so steady-state
serving never recompiles.  Each scheduler iteration:

1. **admit** — move queued requests into free slots and enqueue a
   prefill job per admission; the shared-prefix cache
   (:class:`~polyaxon_tpu.serving.paging.PrefixCache`) maps any cached
   block-prefix of the prompt straight into the request's table (a
   block-aligned FULL hit copies the last block private first —
   copy-on-write — and recomputes only the final prompt token);
2. **prefill tick** — run ONE chunk (``prefill_chunk`` tokens) of the
   oldest pending prefill via
   :func:`~polyaxon_tpu.models.decode.paged_prefill_chunk`, allocating
   table blocks lazily from the ref-counted
   :class:`~polyaxon_tpu.serving.paging.BlockAllocator`; a long prompt
   therefore interleaves with decode instead of stalling the batch;
3. **step** — one batched decode step advances every active slot one
   token; a slot that faults a new block on an exhausted pool PARKS
   (state and blocks kept, active mask cleared — still just data) and
   resumes when references drop;
4. **retire** — finished slots free their blocks back to the pool
   (shared prefix blocks merely drop one reference) and publish their
   prompt blocks to the prefix cache for the next request.

Tokens stream back per-request as they land; ``cancel()`` releases a
request's slot, blocks, and prefix references immediately, and
``stop()`` drains deterministically — every still-pending request gets
an error and exactly one ``None`` stream sentinel.  Greedy outputs are
token-identical to sequential
:func:`~polyaxon_tpu.models.decode.generate` calls with paging, prefix
sharing, and chunked prefill all enabled
(tests/test_serving/test_paging.py asserts it per request).

Sharded + quantized serving compose exactly like the slot-granular
path did: place the params (and the int8 ``(q, scale)`` tree) with
``decode_param_shardings`` / ``quantized_weight_shardings`` and GSPMD
propagates head-sharding through the chunked prefill and the paged
step — the block pool lives on the gang mesh.
"""

from __future__ import annotations

import itertools
import queue
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_int, knob_str
from polyaxon_tpu.serving.paging import (
    BlockAllocator,
    HostKVTier,
    PrefixCache,
    truncate_table,
)
from polyaxon_tpu.stats import MemoryStats
from polyaxon_tpu.stats.tsdb import RatioWindow
from polyaxon_tpu.tracking.flightrec import get_progress
from polyaxon_tpu.tracking.trace import TraceContext, get_tracer


class EngineDrainingError(RuntimeError):
    """Raised by :meth:`ServingEngine.submit` once :meth:`drain` has been
    called — the engine finishes in-flight work but admits nothing new."""


#: Typed per-request speculative modes (``GenerationRequest.spec_mode``):
#: ``off`` (engine not speculating), ``greedy`` (drafted + verified), or
#: ``fallback:sampled`` (temperature>0 — sampling must see the model's
#: real distribution each step, so the request transparently rides
#: single-token rows of the batch; counted on ``spec_fallback_total``).
SPEC_MODE_OFF = "off"
SPEC_MODE_GREEDY = "greedy"
SPEC_MODE_FALLBACK_SAMPLED = "fallback:sampled"


class NgramDrafter:
    """Per-request prompt-lookup drafter (self-drafting, no draft model).

    Keeps the request's full context (prompt + every accepted token) and
    a suffix index mapping each ``n``-gram to the END positions of its
    two most recent occurrences.  ``draft(k)`` matches the context's
    last ``n`` tokens against the index and proposes the continuation of
    the previous occurrence — the prompt-lookup scheme (Saxena), which
    wins exactly on templated/repetitive traffic.  O(1) per appended
    token and per lookup; the index is built during prefill (over the
    prompt) and updated per accepted token, so draft cost never scales
    with context length.
    """

    __slots__ = ("n", "tokens", "_index")

    def __init__(self, n: int) -> None:
        if n < 1:
            raise ValueError(f"ngram length must be positive, got {n}")
        self.n = int(n)
        self.tokens: List[int] = []
        # ngram -> (second-latest end, latest end).  Two-deep because the
        # context's own suffix is always the LATEST occurrence of itself;
        # drafting wants the one before it.
        self._index: Dict[tuple, tuple] = {}

    def extend(self, toks) -> None:
        for t in toks:
            self.append(int(t))

    def append(self, tok: int) -> None:
        self.tokens.append(int(tok))
        if len(self.tokens) >= self.n:
            key = tuple(self.tokens[-self.n :])
            prev = self._index.get(key)
            self._index[key] = (prev[1] if prev else None, len(self.tokens))

    def draft(self, k: int) -> List[int]:
        """Up to ``k`` proposed continuation tokens ([] = no match)."""
        t = self.tokens
        if k < 1 or len(t) < self.n:
            return []
        ends = self._index.get(tuple(t[-self.n :]))
        if ends is None:
            return []
        end = ends[1] if ends[1] < len(t) else ends[0]
        if end is None:
            return []
        return t[end : end + k]


class _RequestTrace:
    """Per-request distributed-trace state.

    ``ctx`` is the propagated :class:`TraceContext` (one trace id across
    router → replica → engine); ``root_id`` is the engine-side request
    span every phase span parents to.  ``park_s`` accumulates wall time
    spent parked so the waterfall can split decode wall-clock into
    device time vs capacity stalls.  Phase accounting is *interval*
    based (queue_wait / prefill / decode / parked partition the
    request's wall clock), so the waterfall always sums to the server-
    side total regardless of how many sub-spans were hot-sampled away.
    """

    __slots__ = ("ctx", "root_id", "parked_at", "park_s", "ttft_s")

    def __init__(self, ctx: TraceContext, root_id: str) -> None:
        self.ctx = ctx
        self.root_id = root_id
        self.parked_at: Optional[float] = None
        self.park_s = 0.0
        self.ttft_s: Optional[float] = None


class _SlowExemplars:
    """Bounded ring of the N slowest fully-traced requests per window.

    ``offer`` keeps the slowest ``n`` finished-request trace summaries
    whose finish time falls inside the sliding window; ``snapshot``
    returns them slowest-first.  Exposed on ``/v1/stats`` and attached
    as the artifact when the ``serving_ttft_p99`` alert fires, so every
    SLO breach ships its own explanation.
    """

    def __init__(self, n: int, window_s: float) -> None:
        self.n = int(n)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._entries: List[Dict[str, Any]] = []

    def offer(self, summary: Dict[str, Any]) -> None:
        if self.n <= 0:
            return
        now = time.time()
        with self._lock:
            self._entries = [
                e
                for e in self._entries
                if now - e.get("finished_at", now) <= self.window_s
            ]
            self._entries.append(summary)
            self._entries.sort(
                key=lambda e: e.get("total_s", 0.0), reverse=True
            )
            del self._entries[self.n :]

    def snapshot(self) -> List[Dict[str, Any]]:
        now = time.time()
        with self._lock:
            return [
                dict(e)
                for e in self._entries
                if now - e.get("finished_at", now) <= self.window_s
            ]


class GenerationRequest:
    """One queued generation: its prompt, its budget, and its results.

    ``stream`` yields token ids as they are generated (a ``None``
    sentinel marks completion); ``done`` is set when the request has
    finished (or failed — see ``error``; ``error_kind`` is the
    machine-readable class: ``shed`` / ``cancelled`` / ``stopped``).
    ``tokens`` accumulates the generated ids in order.
    """

    _ids = itertools.count()

    def __init__(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
    ) -> None:
        self.id = next(self._ids)
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.tokens: List[int] = []
        self.spec_mode: str = SPEC_MODE_OFF
        self.stream: "queue.Queue[Optional[int]]" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.error_kind: Optional[str] = None
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        #: Distributed-trace state (None = untraced request).
        self.trace: Optional[_RequestTrace] = None
        #: Waterfall summary, filled once when the request finishes.
        self.trace_summary: Optional[Dict[str, Any]] = None

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Block until done; raise on engine-side failure."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens


class SlotAllocator:
    """FIFO free-list over ``n`` cache slots.

    Freed slots go to the BACK of the list, so reuse order is the order
    slots were released — the coldest slot is reused first, which keeps
    any one slot's stale KV rows short-lived (and makes the admit/evict/
    reuse sequence deterministic for tests).
    """

    def __init__(self, n: int) -> None:
        if n < 1:
            raise ValueError(f"need at least one slot, got {n}")
        self.n = n
        self._free: deque = deque(range(n))
        self._held: set = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        slot = self._free.popleft()
        self._held.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._held:
            raise ValueError(f"slot {slot} is not allocated")
        self._held.discard(slot)
        self._free.append(slot)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return len(self._held)


class _PrefillJob:
    """One admitted request's remaining prompt insertion, advanced one
    chunk per scheduler iteration."""

    __slots__ = ("req", "slot", "next_pos", "cow_pending")

    def __init__(self, req: GenerationRequest, slot: int) -> None:
        self.req = req
        self.slot = slot
        self.next_pos = 0  # first prompt position not yet inserted
        self.cow_pending = False  # full prefix hit: copy last block first


class ServingEngine:
    """The continuous-batching scheduler: one thread owns the device.

    Parameters
    ----------
    params, cfg : the model (a ``TransformerConfig`` tree).
    slots : concurrent sequences the batch holds (the static batch dim).
    max_len : per-request sequence capacity (default ``cfg.max_seq``).
    block_size : tokens per KV block — the paging granularity.  Smaller
        blocks waste less tail capacity and share shorter prefixes;
        larger blocks shrink tables and gather indices.
    num_blocks : physical pool size INCLUDING the reserved trash block.
        Defaults to ``1 + slots * ceil(max_len / block_size)`` — enough
        for every slot to reach ``max_len`` with no sharing, i.e. the
        old slot-granular footprint plus one block.  Size it below that
        to overcommit on prefix sharing: exhaustion parks decodes until
        references drop (and sheds the newest blocked request if nobody
        can ever free one).
    prefill_chunk : prompt tokens inserted per scheduler iteration.
        ``None`` inserts each prompt whole (one chunk); a finite chunk
        bounds how long any prefill can stall the decode batch, which
        is what keeps TTFT p99 flat under load.
    prefix_cache : share KV blocks between requests with identical
        token-block prefixes (copy-on-write at the divergence point).
    qweights : int8 tree from ``decode.quantize_weights`` — the paged
        step streams int8 exactly like the slot step did.
    kv_quantize : ``"int8"`` stores the KV pool itself quantized —
        int8 rows plus one f32 scale per (block row, kv head), under
        0.3× the f32 pool's HBM at the same ``num_blocks`` — so a fixed
        memory budget holds >2× the live blocks.  Appends quantize
        once; attention reads dequantize fused into the gather (the
        ``_wdq`` pattern applied to KV).  Composes with ``qweights``
        (weight int8) and with prefix sharing/COW, which copy the
        quantized leaves bit-exact.  ``None``/falsey keeps the full
        compute-dtype pool.  Greedy outputs are near- but not bit-
        identical to the full-precision pool (see docs/serving.md).
    mesh / param_shardings / qweights_shardings : multi-chip serving;
        when given, params (and qweights) are placed on the mesh and
        GSPMD propagates the sharding through prefill and the step.
    eos_id : optional token id that retires a slot early.
    seed : RNG seed for the sampling path (greedy ignores it).
    warmup : pre-compile the whole compiled-fn family (the decode step,
        every prefill chunk bucket up to ``prefill_chunk``, the COW copy
        fn) at the top of the scheduler loop before serving traffic, so
        the first request never eats a compile.  ``wait_ready()`` blocks
        on the gate; ``stats()['state']`` reports ``warming|ready``.
        With the persistent compile cache armed
        (``runtime/compilecache.py``) a restarted replica warms from
        disk instead of compiling cold.  ``False`` skips straight to
        ready — compiles then happen lazily mid-traffic and show up on
        the ``serving.steady_state_compiles`` counter.  Default (None)
        reads ``POLYAXON_TPU_SERVING_WARMUP`` (on unless ``0``/``false``
        /``off``).
    spec_decode / spec_k / spec_min_ngram : speculative decoding — a
        host-side prompt-lookup drafter proposes up to ``spec_k``
        continuation tokens per greedy lane (matching the context's last
        ``spec_min_ngram`` tokens against the request's own suffix
        index) and ONE ``paged_verify_step`` scores the whole run;
        accepted tokens append, the block table rolls back past the
        rejection point.  Greedy outputs stay token-identical to the
        non-speculative engine (the accept rule emits exactly the
        model's own argmax run); temperature>0 requests transparently
        fall back to single-token rows (``spec_fallback_total``).
        Defaults read the ``POLYAXON_TPU_SERVING_SPEC_*`` knobs (off).
    kv_offload / kv_offload_blocks : the host-memory KV tier.  When on,
        a parked sequence's private blocks spill to host memory (pinned
        — parking RELEASES pool capacity instead of sitting on it, so
        oversubscription costs restore latency instead of sheds) and
        cold prefix-cache entries DEMOTE to the tier instead of hard-
        evicting (a later hit restores them through a fresh block; the
        verify-on-hit token compare is unchanged).  Both copies move the
        pool's storage leaves bit-exact — an int8 pool spills int8 rows
        + scales, values never requantize.  ``kv_offload_blocks`` bounds
        the DEMOTED population (LRU drop; 0 = unbounded); pinned spills
        never count.  Defaults read ``POLYAXON_TPU_KV_OFFLOAD`` /
        ``POLYAXON_TPU_KV_OFFLOAD_BLOCKS``.
    kv_persist_dir / kv_persist_blocks / kv_persist_sig : the persistent
        prefix store (``serving/kvstore.py``; point ``kv_persist_dir``
        at ``StoreLayout.kv_cache_dir``).  The engine snapshots its
        hottest ``kv_persist_blocks`` prefix blocks — torn-write-safe,
        idle-time throttled by ``POLYAXON_TPU_KV_PERSIST_INTERVAL_S``,
        plus a final snapshot on ``stop()`` — and warmup preloads the
        newest complete snapshot before the ready gate opens, so a
        replacement/scale-up replica serves its first request
        prefix-warm.  ``kv_persist_sig`` is the model-identity
        fingerprint stored with (and required of) a snapshot: pass
        something that changes with the weights (seed, checkpoint step)
        so a replica never preloads KV another model computed.  Left
        empty, the engine derives one by fingerprinting the weights
        themselves (geometry can't tell checkpoints apart, so an
        unsigned store is never written).
        Defaults read the ``POLYAXON_TPU_KV_PERSIST_*`` knobs (off).
    stats : a stats backend receiving latency histograms
        (``serving.queue_wait_s`` / ``serving.ttft_s`` /
        ``serving.decode_step_s`` / ``serving.batch_occupancy``) and
        paging gauges (``serving.block_occupancy`` /
        ``serving.prefix_cache_hit_rate`` /
        ``serving.prefill_backlog_chunks``); defaults to a private
        :class:`MemoryStats` — ``lm_server`` passes the process-wide
        registry so ``/metrics`` exports them.
    """

    #: Padding buckets for prompt chunks: powers of two bound the number
    #: of prefill compilations at log2(max_len) regardless of traffic.
    @staticmethod
    def _bucket(t: int, max_len: int) -> int:
        b = 8
        while b < t:
            b *= 2
        return min(b, max_len)

    def __init__(
        self,
        params: Any,
        cfg: Any,
        *,
        slots: int = 4,
        max_len: Optional[int] = None,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        prefix_cache: bool = True,
        qweights: Optional[Any] = None,
        kv_quantize: Optional[str] = None,
        mesh: Any = None,
        param_shardings: Optional[Any] = None,
        qweights_shardings: Optional[Any] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        stats: Optional[Any] = None,
        warmup: Optional[bool] = None,
        spec_decode: Optional[bool] = None,
        spec_k: Optional[int] = None,
        spec_min_ngram: Optional[int] = None,
        kv_offload: Optional[bool] = None,
        kv_offload_blocks: Optional[int] = None,
        kv_persist_dir: Optional[str] = None,
        kv_persist_blocks: Optional[int] = None,
        kv_persist_sig: str = "",
    ) -> None:
        import jax

        from polyaxon_tpu.models import decode

        if max_len is None:
            max_len = cfg.max_seq
        if max_len > cfg.max_seq:
            raise ValueError(
                f"max_len ({max_len}) exceeds the model's max_seq "
                f"({cfg.max_seq})"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be positive or None, got {prefill_chunk}"
            )
        self.cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.block_size = int(block_size)
        self.prefill_chunk = prefill_chunk
        self.eos_id = eos_id
        self._mesh = mesh
        if param_shardings is not None:
            params = jax.device_put(params, param_shardings)
        if qweights is not None and qweights_shardings is not None:
            qweights = jax.device_put(qweights, qweights_shardings)
        self._params = params
        self._qweights = qweights

        # Table width: logical blocks a max_len sequence spans.  The
        # default pool matches the old slot-granular footprint (every
        # slot can reach max_len unshared) plus the trash block.
        self._table_width = -(-self.max_len // self.block_size)
        if num_blocks is None:
            num_blocks = 1 + self.slots * self._table_width
        self.block_allocator = BlockAllocator(num_blocks)
        self.prefix_cache = (
            PrefixCache(self.block_allocator, self.block_size)
            if prefix_cache
            else None
        )
        kvq = "" if kv_quantize in (None, False) else str(kv_quantize).lower()
        if kvq in ("", "0", "false", "no", "off", "none"):
            self.kv_quantize: Optional[str] = None
        elif kvq in ("1", "true", "yes", "on", "int8"):
            self.kv_quantize = "int8"
        else:
            raise ValueError(
                f"unsupported kv_quantize {kv_quantize!r} (int8 or off)"
            )
        self._pool = decode.init_block_pool(
            cfg, num_blocks, self.block_size, kv_dtype=self.kv_quantize
        )
        #: What the pool leaves actually store ("int8" or the compute
        #: dtype name) and their total device bytes — surfaced on
        #: ``/v1/stats``, the ``serving.kv_pool_bytes`` gauge, and the
        #: final ledger row so goodput HBM accounting sees pool shrink.
        self.kv_dtype = self.kv_quantize or str(jax.numpy.dtype(cfg.dtype).name)
        self.kv_pool_bytes = int(
            sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(self._pool))
        )
        # Per-slot block tables (host truth): -1 = unset, mapped to the
        # trash block when shipped to the device.
        self._tables = np.full(
            (self.slots, self._table_width), -1, np.int32
        )

        # Host-side per-slot state: the NEXT token to feed, its absolute
        # position, the active mask, and each slot's sampling temperature.
        self._tok = np.zeros(self.slots, np.int32)
        self._pos = np.zeros(self.slots, np.int32)
        self._active = np.zeros(self.slots, bool)
        self._temps = np.zeros(self.slots, np.float32)
        self._slot_req: List[Optional[GenerationRequest]] = [None] * self.slots

        self.allocator = SlotAllocator(self.slots)
        self._queue: "deque[GenerationRequest]" = deque()
        self._prefill: "deque[_PrefillJob]" = deque()
        self._parked: List[int] = []
        self._cancels: set = set()
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._draining = False
        self._thread: Optional[threading.Thread] = None

        # Warmup / readiness gate: the scheduler thread compiles the fn
        # family before its first iteration; requests submitted while
        # warming just queue.  The steady-state compile counter watches
        # total jit cache size growth after ready — the "zero
        # steady-state recompiles" invariant, monitored in production
        # rather than only asserted in tests.
        if warmup is None:
            warmup = knob_bool("POLYAXON_TPU_SERVING_WARMUP")
        self._warmup = bool(warmup)
        self._ready = threading.Event()
        self._warmup_total = 0
        self._warmup_done = 0
        self._warmup_s = 0.0
        self._n_steady_compiles = 0
        self._compiled_baseline: Optional[int] = None

        # Speculative decoding: self-drafting multi-token steps.  All
        # three default from the POLYAXON_TPU_SERVING_SPEC_* knobs.
        if spec_decode is None:
            spec_decode = knob_bool("POLYAXON_TPU_SERVING_SPEC_DECODE")
        self.spec_decode = bool(spec_decode)
        self.spec_k = int(
            spec_k if spec_k is not None
            else knob_int("POLYAXON_TPU_SERVING_SPEC_K")
        )
        self.spec_min_ngram = int(
            spec_min_ngram if spec_min_ngram is not None
            else knob_int("POLYAXON_TPU_SERVING_SPEC_MIN_NGRAM")
        )
        if self.spec_decode and self.spec_k < 1:
            raise ValueError(f"spec_k must be positive, got {self.spec_k}")
        if self.spec_decode and self.spec_min_ngram < 1:
            raise ValueError(
                f"spec_min_ngram must be positive, got {self.spec_min_ngram}"
            )
        #: Per-slot drafter (None: slot empty, spec off, or the request
        #: is sampled — the typed fallback path).
        self._drafters: List[Optional[NgramDrafter]] = [None] * self.slots
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_fallbacks = 0
        self._spec_steps = 0

        # Hierarchical KV: the host offload tier and the persistent
        # prefix store, both defaulting from the POLYAXON_TPU_KV_* knobs.
        if kv_offload is None:
            kv_offload = knob_bool("POLYAXON_TPU_KV_OFFLOAD")
        self.kv_offload = bool(kv_offload)
        self.kv_offload_blocks = int(
            kv_offload_blocks if kv_offload_blocks is not None
            else knob_int("POLYAXON_TPU_KV_OFFLOAD_BLOCKS")
        )
        if kv_persist_dir is None:
            kv_persist_dir = knob_str("POLYAXON_TPU_KV_PERSIST_DIR")
        self.kv_persist_dir = str(kv_persist_dir) if kv_persist_dir else None
        self.kv_persist_blocks = int(
            kv_persist_blocks if kv_persist_blocks is not None
            else knob_int("POLYAXON_TPU_KV_PERSIST_BLOCKS")
        )
        self.kv_persist_sig = str(kv_persist_sig or "")
        if self.kv_persist_dir and not self.kv_persist_sig:
            # No model identity provided: the store meta's geometry +
            # dtype cannot tell two checkpoints of the same config
            # apart, and an empty sig would let replicas serving
            # DIFFERENT weights exchange KV through a shared store.
            # Derive a fingerprint from the weights themselves; if that
            # fails, disable persistence rather than silently allow it.
            self.kv_persist_sig = self._auto_persist_sig(
                params, qweights, seed
            )
            if not self.kv_persist_sig:
                import warnings

                warnings.warn(
                    "kv_persist_dir is set but no kv_persist_sig was "
                    "given and no weight fingerprint could be derived; "
                    "disabling KV persistence (an unsigned shared store "
                    "could serve KV computed by a different model)",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self.kv_persist_dir = None
        self._kv_persist_interval_s = knob_float(
            "POLYAXON_TPU_KV_PERSIST_INTERVAL_S"
        )
        self._host_tier = (
            HostKVTier(self.kv_offload_blocks) if self.kv_offload else None
        )
        self._export_fn: Optional[Any] = None
        self._import_fn: Optional[Any] = None
        #: Parked-sequence spill map: slot -> {table index: tier handle}.
        self._spilled: Dict[int, Dict[int, int]] = {}
        self._n_spilled_blocks = 0
        self._n_restored_blocks = 0
        self._n_shed = 0
        self._kv_preloaded_blocks = 0
        self._kv_persisted_blocks = 0
        self._last_persist_t = 0.0
        self._last_persist_mut = -1
        if self._host_tier is not None and self.prefix_cache is not None:
            self.prefix_cache.attach_tier(
                self._host_tier,
                spill=self._spill_to_tier,
                restore=self._restore_from_tier,
                alloc=self._alloc_block,
            )

        self._key = jax.random.PRNGKey(seed)
        self._rng = np.random.default_rng(seed)
        self._chunk_fns: Dict[int, Any] = {}
        self._copy_fn: Optional[Any] = None
        self._verify_fns: Dict[int, Any] = {}
        self._step_fn = self._build_step()

        # Stats: lifetime counters plus a sliding window for tokens/s;
        # latency distributions go to the (possibly shared) histogram
        # registry so /metrics can export percentiles.
        self.stats_registry = stats if stats is not None else MemoryStats()
        # Decode ticks feed the process's stall watchdog: a serving worker
        # that stops emitting tokens is as stuck as a hung train step.
        self._progress = get_progress()
        # On-demand capture (control-plane `profile` commands): decode
        # iterations drive the same per-step hook trainers use, so a
        # capture window is N decode steps.  Gated on the readiness event
        # in _step_once — a warmup compile storm is not steady-state
        # serving and must not satisfy a profile command's window.
        from polyaxon_tpu.tracking.capture import get_capture_agent

        self._capture = get_capture_agent()
        self._stats_lock = threading.Lock()
        self._n_submitted = 0
        self._n_finished = 0
        self._n_cancelled = 0
        self._n_tokens = 0
        self._n_steps = 0
        self._n_parks = 0
        self._n_cow = 0
        self._backlog_chunks = 0
        self._prefill_jobs = 0
        self._window: "deque[tuple]" = deque()  # (t, n_tokens)
        # Windowed variants of the lifetime cumulative ratios exposed by
        # /v1/stats: dashboards and the router's affinity slack should
        # see current behavior, not boot-averaged history.  Horizon 2× so
        # the baseline sample at-or-before the window start survives.
        self._stats_window_s = knob_float("POLYAXON_TPU_SERVING_STATS_WINDOW_S")
        self._pc_window = RatioWindow(self._stats_window_s * 2.0)
        self._spec_window = RatioWindow(self._stats_window_s * 2.0)
        # Request-scoped distributed tracing: master switch plus the
        # slow-request exemplar ring (`/v1/stats` + the serving_ttft_p99
        # alert's attached artifact).
        self.trace_requests = knob_bool("POLYAXON_TPU_TRACE_REQUESTS")
        self._exemplars = _SlowExemplars(
            knob_int("POLYAXON_TPU_TRACE_EXEMPLARS"),
            knob_float("POLYAXON_TPU_TRACE_EXEMPLAR_WINDOW_S"),
        )
        # Decode-side utilization ledger (armed in start()): device-busy
        # seconds (prefill + decode dispatch/sync) and occupancy-weighted
        # busy time — the serving analogue of train-side goodput/MFU.
        self._ledger: Optional[Any] = None
        self._started_at: Optional[float] = None
        self._busy_s = 0.0
        self._occ_weighted_s = 0.0

    # -- compiled functions ----------------------------------------------------

    def _donate(self) -> tuple:
        # Pool donation halves peak memory for the engine's largest
        # buffer — and without it every chunk/step call COPIES the whole
        # pool on its way out, a per-call cost that grows with the pool
        # and multiplies under chunked prefill.  All current backends
        # (CPU included) honor donation for same-shape aliasing.
        return (1,)

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.decode import paged_decode_step

        cfg = self.cfg

        def step(params, pool, tables, tokens, pos, active, temps, key, qweights):
            logits, pool = paged_decode_step(
                params, pool, tables, tokens, pos, active, cfg,
                qweights=qweights,
            )
            greedy_tok = jnp.argmax(logits, axis=-1)
            # Per-slot keys: a slot's sample must not depend on which
            # neighbors happen to be in flight.
            keys = jax.random.split(key, logits.shape[0])
            safe = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(jax.random.categorical)(
                keys, logits / safe[:, None]
            )
            tok = jnp.where(temps > 0, sampled, greedy_tok)
            return jnp.where(active, tok, 0).astype(jnp.int32), pool

        return jax.jit(step, donate_argnums=self._donate())

    def _get_chunk(self, c_pad: int):
        import jax

        from polyaxon_tpu.models.decode import paged_prefill_chunk

        if c_pad not in self._chunk_fns:
            cfg = self.cfg

            def chunk_fn(params, pool, table, tokens, start, length):
                return paged_prefill_chunk(
                    params, pool, table, tokens, start, length, cfg
                )

            self._chunk_fns[c_pad] = jax.jit(
                chunk_fn, donate_argnums=(1,) if self._donate() else ()
            )
        return self._chunk_fns[c_pad]

    def _get_copy(self):
        import jax

        from polyaxon_tpu.models.decode import copy_block

        if self._copy_fn is None:
            self._copy_fn = jax.jit(
                copy_block, donate_argnums=(0,) if self._donate() else ()
            )
        return self._copy_fn

    def _get_export(self):
        import jax

        from polyaxon_tpu.models.decode import export_block

        if self._export_fn is None:
            # NO donation, deliberately: the slice's result must be an
            # independent buffer, because the pool is donated to every
            # later step/chunk/import call — the runtime orders the read
            # before any subsequent donated write, which is what lets the
            # device→host copy drain while serving moves on.
            self._export_fn = jax.jit(export_block)
        return self._export_fn

    def _get_import(self):
        import jax

        from polyaxon_tpu.models.decode import import_block

        if self._import_fn is None:
            self._import_fn = jax.jit(
                import_block, donate_argnums=(0,) if self._donate() else ()
            )
        return self._import_fn

    def _export_blocks(self, blocks: List[int]) -> List[Dict[str, np.ndarray]]:
        """Device→host copy of ``blocks``' payloads, double-buffered:
        every block's slice is DISPATCHED before any is materialized, so
        block i+1's device-side copy overlaps block i's host conversion
        — the ``runtime/pipeline.py`` prefetch idea applied to spill."""
        import jax.numpy as jnp

        fn = self._get_export()
        pending = [fn(self._pool, jnp.int32(b)) for b in blocks]
        return [
            {name: np.asarray(leaf) for name, leaf in tree.items()}
            for tree in pending
        ]

    def _import_block(self, block: int, data: Dict[str, np.ndarray]) -> None:
        """Host→device copy of one payload into pool block ``block``."""
        import jax.numpy as jnp

        self._pool = self._get_import()(self._pool, data, jnp.int32(block))
        with self._stats_lock:
            self._n_restored_blocks += 1

    def _spill_to_tier(self, block: int) -> Optional[int]:
        """PrefixCache demotion callback: move one cached block's payload
        host-side; returns its tier handle (None = tier refused, entry
        hard-evicts instead)."""
        [data] = self._export_blocks([block])
        handle = self._host_tier.put(data, pinned=False)
        if handle is not None:
            with self._stats_lock:
                self._n_spilled_blocks += 1
        return handle

    def _restore_from_tier(self, handle: int, block: int) -> None:
        """PrefixCache restore callback: write a demoted entry's payload
        back into the freshly allocated device block."""
        self._import_block(block, self._host_tier.pop(handle))

    def _spec_widths(self) -> List[int]:
        """The verify-step width family: draft-count buckets (powers of
        two capped at ``spec_k``) plus one row for the current token.
        Bucketing bounds compilations at log2(spec_k) whatever draft-
        length mix live traffic produces; ``n_tok`` is data inside each
        bucket."""
        if not self.spec_decode:
            return []
        out = set()
        k = 1
        while k < self.spec_k:
            out.add(k + 1)
            k *= 2
        out.add(self.spec_k + 1)
        return sorted(out)

    def _width_for(self, max_draft: int) -> int:
        """Smallest warm verify width that fits ``max_draft`` drafts."""
        for w in self._spec_widths():
            if w >= max_draft + 1:
                return w
        return self.spec_k + 1

    def _get_verify(self, width: int):
        """The jitted verify step for one padded draft width: the kernel
        plus on-device accept/sample resolution, so only [S, width]
        tokens and [S] emit counts ever cross back to the host."""
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.decode import paged_verify_step

        if width not in self._verify_fns:
            cfg = self.cfg

            def verify(
                params, pool, tables, tokens, pos, n_tok, active, temps,
                key, qweights,
            ):
                logits, pool = paged_verify_step(
                    params, pool, tables, tokens, pos, n_tok, active, cfg,
                    qweights=qweights,
                )
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # Row 0 is always emitted; sampled lanes (which never
                # draft) sample it exactly like the single-token step.
                keys = jax.random.split(key, logits.shape[0])
                safe = jnp.where(temps > 0, temps, 1.0)
                sampled = jax.vmap(jax.random.categorical)(
                    keys, logits[:, 0] / safe[:, None]
                )
                first = jnp.where(
                    temps > 0, sampled, greedy[:, 0]
                ).astype(jnp.int32)
                out = jnp.concatenate([first[:, None], greedy[:, 1:]], axis=1)
                # Accept mask: draft j+1 survives iff it equals the
                # model's own pick after row j AND every draft before it
                # survived (cumprod) — the Leviathan greedy accept rule.
                drafts_ok = (
                    jnp.arange(1, tokens.shape[1])[None, :] < n_tok[:, None]
                ) & (temps[:, None] <= 0)
                match = (tokens[:, 1:] == greedy[:, :-1]) & drafts_ok
                n_emit = 1 + jnp.cumprod(
                    match.astype(jnp.int32), axis=1
                ).sum(axis=1)
                out = jnp.where(active[:, None], out, 0)
                n_emit = jnp.where(active, n_emit, 0).astype(jnp.int32)
                return out, n_emit, pool

            self._verify_fns[width] = jax.jit(
                verify, donate_argnums=self._donate()
            )
        return self._verify_fns[width]

    def _compiled_count(self) -> int:
        """Total compiled entries across the engine's jitted fns (0 when
        the jax version exposes no ``_cache_size``)."""
        fns = [
            self._step_fn,
            *self._chunk_fns.values(),
            *self._verify_fns.values(),
        ]
        if self._copy_fn is not None:
            fns.append(self._copy_fn)
        if self._export_fn is not None:
            fns.append(self._export_fn)
        if self._import_fn is not None:
            fns.append(self._import_fn)
        n = 0
        for fn in fns:
            try:
                n += int(fn._cache_size())
            except Exception:
                pass
        return n

    def _warmup_buckets(self) -> List[int]:
        """The chunk-bucket family live traffic can mint: every
        ``_bucket`` value for chunk lengths up to ``prefill_chunk`` (the
        whole prompt when unchunked), capped at ``max_len``."""
        cap = min(self.prefill_chunk or self.max_len, self.max_len)
        out = set()
        b = 8
        while True:
            out.add(min(b, self.max_len))
            if b >= cap:
                break
            b *= 2
        return sorted(out)

    def _run_warmup(self) -> None:
        """Compile the whole fn family before serving traffic (scheduler
        thread, before its first iteration — it owns the pool, so there
        is no device race with live requests, which queue meanwhile).

        Every call EXECUTES its fn — ``lower().compile()`` would not
        populate the jit dispatch cache — with arguments whose writes
        all land in the reserved trash block 0: the decode step with an
        all-inactive mask, each chunk bucket with ``length=0``, and the
        COW copy as a trash self-copy.  Failures degrade to lazy
        compiles (counted by the steady-state monitor) rather than
        killing the engine; the readiness gate opens regardless.
        """
        import jax
        import jax.numpy as jnp

        tracer = get_tracer()
        t0 = time.perf_counter()
        # Warm replica boot: hydrate the prefix cache from the persisted
        # store BEFORE the ready gate opens, so a scale-up replica's
        # first request already walks a warm cache.  Best-effort —
        # a missing/torn/mismatched store just boots cold.
        try:
            self._preload_prefixes()
        except Exception:
            pass
        spillers = self._host_tier is not None or bool(self.kv_persist_dir)
        buckets = self._warmup_buckets() if self._warmup else []
        widths = self._spec_widths() if self._warmup else []
        self._warmup_total = (
            len(buckets) + len(widths) + 2 + (1 if spillers else 0)
            if self._warmup
            else 0
        )
        gauge = getattr(self.stats_registry, "gauge", None)

        def _tick() -> None:
            self._warmup_done += 1
            if gauge is not None and self._warmup_total:
                gauge(
                    "serving.warmup_progress",
                    self._warmup_done / self._warmup_total,
                )

        try:
            if self._warmup:
                with tracer.span("serving.warmup", buckets=len(buckets)):
                    self._key, sub = jax.random.split(self._key)
                    tables = np.where(
                        self._tables >= 0, self._tables, 0
                    ).astype(np.int32)
                    toks, self._pool = self._step_fn(
                        self._params,
                        self._pool,
                        jnp.asarray(tables),
                        jnp.asarray(self._tok),
                        jnp.asarray(self._pos),
                        jnp.asarray(self._active),
                        jnp.asarray(self._temps),
                        sub,
                        self._qweights,
                    )
                    jax.block_until_ready(toks)
                    _tick()
                    table0 = jnp.zeros(self._table_width, jnp.int32)
                    for c_pad in buckets:
                        if self._stop.is_set():
                            break
                        logits, self._pool = self._get_chunk(c_pad)(
                            self._params,
                            self._pool,
                            table0,
                            jnp.zeros(c_pad, jnp.int32),
                            jnp.int32(0),
                            jnp.int32(0),
                        )
                        jax.block_until_ready(logits)
                        _tick()
                    # The verify family: every width bucket speculative
                    # traffic can request, warmed all-inactive so writes
                    # land in the trash block.
                    for width in widths:
                        if self._stop.is_set():
                            break
                        self._key, sub = jax.random.split(self._key)
                        out, n_emit, self._pool = self._get_verify(width)(
                            self._params,
                            self._pool,
                            jnp.asarray(tables),
                            jnp.zeros((self.slots, width), jnp.int32),
                            jnp.asarray(self._pos),
                            jnp.ones(self.slots, jnp.int32),
                            jnp.asarray(self._active),
                            jnp.asarray(self._temps),
                            sub,
                            self._qweights,
                        )
                        jax.block_until_ready(out)
                        _tick()
                    self._pool = self._get_copy()(
                        self._pool, jnp.int32(0), jnp.int32(0)
                    )
                    jax.block_until_ready(self._pool)
                    _tick()
                    if spillers:
                        # Spill/restore round trip through the trash
                        # block: compiles export+import so steady-state
                        # park-spill and demotion never mint a compile.
                        [data] = self._export_blocks([0])
                        self._pool = self._get_import()(
                            self._pool, data, jnp.int32(0)
                        )
                        jax.block_until_ready(self._pool)
                        _tick()
        except Exception:
            pass
        finally:
            self._warmup_s = time.perf_counter() - t0
            self._compiled_baseline = self._compiled_count()
            # Lazy HLO source for on-demand captures: lowering text is only
            # produced if a profile command actually fires (no extra
            # compile — .lower() stops before XLA).
            self._capture.register_executable(
                "serving_decode_step",
                type("_LazyHLO", (), {"as_text": lambda _s: self._decode_hlo_text()})(),
            )
            self._ready.set()
            if gauge is not None:
                gauge("serving.warmup_progress", 1.0)

    def _decode_hlo_text(self) -> str:
        """Lower the decode step against the engine's live shapes and
        render its HLO text (capture-time only; best-effort)."""
        import jax.numpy as jnp

        tables = np.where(self._tables >= 0, self._tables, 0).astype(np.int32)
        lowered = self._step_fn.lower(
            self._params,
            self._pool,
            jnp.asarray(tables),
            jnp.asarray(self._tok),
            jnp.asarray(self._pos),
            jnp.asarray(self._active),
            jnp.asarray(self._temps),
            self._key,
            self._qweights,
        )
        return lowered.as_text()

    def _check_steady_compiles(self) -> None:
        """Post-ready jit cache growth = a steady-state compile stalled
        the batch (a config edge bucket, a changed donation layout):
        record an ``engine.compile`` span + counter so the invariant is
        observable, not just asserted in tests."""
        if self._compiled_baseline is None:
            return
        n = self._compiled_count()
        grew = n - self._compiled_baseline
        if grew <= 0:
            return
        self._compiled_baseline = n
        with self._stats_lock:
            self._n_steady_compiles += grew
        incr = getattr(self.stats_registry, "incr", None)
        if incr is not None:
            try:
                incr("serving.steady_state_compiles", grew)
            except Exception:
                pass
        with get_tracer().span("engine.compile", n=grew, total=n):
            pass

    # -- public API ------------------------------------------------------------

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the warmup pass has run (or was skipped/failed);
        True when the engine is ready to serve without compiling."""
        return self._ready.wait(timeout)

    def start(self) -> "ServingEngine":
        if self._thread is None:
            from polyaxon_tpu.tracking.ledger import get_ledger

            self._ledger = get_ledger().start(source="serving")
            self._started_at = time.time()
            self._thread = threading.Thread(
                target=self._loop, name="serving-engine", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # Final prefix-store snapshot (scheduler thread is down — the
        # pool is ours again): whatever this replica learned, the next
        # one boots with.
        self._maybe_persist(force=True)
        if self._ledger is not None:
            paging = self._paging_snapshot()
            spec = self._spec_snapshot()
            self._ledger.merge_extra(
                **self._utilization_snapshot(),
                block_occupancy=paging["block_occupancy"],
                prefix_cache_hit_rate=paging["prefix_cache_hit_rate"],
                prefix_cache_hits=paging["prefix_cache_hits"],
                prefix_cache_misses=paging["prefix_cache_misses"],
                prefix_cache_evictions=paging["prefix_cache_evictions"],
                prefix_cache_demotions=paging["prefix_cache_demotions"],
                prefix_cache_restores=paging["prefix_cache_restores"],
                parked_sequences=paging["parked_sequences"],
                requests_shed=paging["requests_shed"],
                host_spilled_blocks_total=paging["host_spilled_blocks_total"],
                host_restored_blocks_total=paging["host_restored_blocks_total"],
                prefill_backlog_chunks=paging["prefill_backlog_chunks"],
                kv_pool_bytes=paging["kv_pool_bytes"],
                kv_dtype=paging["kv_dtype"],
                spec_proposed_total=spec["spec_proposed_total"],
                spec_accepted_total=spec["spec_accepted_total"],
                spec_accept_rate=spec["spec_accept_rate"],
            )
            self._ledger.flush(final=True)
            self._ledger = None
        # Deterministic drain: every request still holding a waiter gets
        # its error and exactly ONE None stream sentinel — queued,
        # mid-prefill, parked, or actively decoding alike (requests in
        # the prefill deque also sit in _slot_req; the id-keyed dict
        # de-dupes them).
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
        drain: Dict[int, GenerationRequest] = {r.id: r for r in pending}
        for job in self._prefill:
            drain.setdefault(job.req.id, job.req)
        self._prefill.clear()
        for req in self._slot_req:
            if req is not None:
                drain.setdefault(req.id, req)
        for req in drain.values():
            if not req.done.is_set():
                req.error = "engine stopped"
                req.error_kind = "stopped"
                self._finalize_trace(req, "stopped")
                req.stream.put(None)
                req.done.set()

    def drain(self) -> None:
        """Stop admitting new requests; in-flight work runs to completion.

        The readiness state flips to ``"draining"`` (so health probes and
        routers stop sending traffic) and :meth:`submit` raises
        :class:`EngineDrainingError`.  Non-blocking — callers poll
        ``stats()`` for ``slots_active == 0 and queue_depth == 0`` to know
        the drain has finished, then :meth:`stop`.
        """
        with self._cv:
            self._draining = True
            self._cv.notify_all()

    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        trace: Optional[TraceContext] = None,
    ) -> GenerationRequest:
        """Validate and enqueue; returns immediately with the request.

        ``trace`` opts the request into distributed tracing: its
        lifecycle phases are recorded as spans under the propagated
        trace id (remote parent = the caller's span), and the finished
        request carries a latency-waterfall ``trace_summary``.
        """
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if any(t < 0 or t >= self.cfg.vocab_size for t in prompt):
            raise ValueError("token id out of vocabulary range")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be positive")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len})"
            )
        needed = -(-(len(prompt) + max_new_tokens) // self.block_size)
        usable = self.block_allocator.num_blocks - 1
        if needed > usable:
            raise ValueError(
                f"request spans {needed} KV blocks but the pool only has "
                f"{usable}; raise num_blocks or shorten the request"
            )
        req = GenerationRequest(prompt, max_new_tokens, temperature)
        if trace is not None and self.trace_requests and trace.sampled:
            req.trace = _RequestTrace(trace, get_tracer().next_span_id())
        with self._cv:
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            if self._draining:
                raise EngineDrainingError(
                    "engine is draining (no new admissions)"
                )
            self._queue.append(req)
            self._n_submitted += 1
            self._cv.notify_all()
        return req

    def cancel(self, request_id: int) -> bool:
        """Best-effort immediate release of one request.

        A queued request fails in place; an in-flight one (prefilling,
        parked, or decoding) is failed by the scheduler thread on its
        next iteration, releasing its slot, KV blocks, and prefix-cache
        references.  Returns ``False`` for unknown or already-finished
        ids.  The cancelled request's waiters observe a ``RuntimeError``
        ("request cancelled") and one ``None`` stream sentinel.
        """
        with self._cv:
            for req in list(self._queue):
                if req.id == request_id:
                    self._queue.remove(req)
                    with self._stats_lock:
                        self._n_cancelled += 1
                    req.error = "request cancelled"
                    req.error_kind = "cancelled"
                    self._finalize_trace(req, "cancelled")
                    req.stream.put(None)
                    req.done.set()
                    return True
            for req in self._slot_req:
                if (
                    req is not None
                    and req.id == request_id
                    and not req.done.is_set()
                ):
                    self._cancels.add(request_id)
                    self._cv.notify_all()
                    return True
        return False

    def generate(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> List[int]:
        """Blocking convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens, temperature).wait(timeout)

    def _utilization_snapshot(self) -> Dict[str, float]:
        """Decode-side utilization: busy fraction of wall clock since
        start(), mean slot occupancy while busy, and their product — the
        serving equivalent of the train ledger's goodput × MFU."""
        with self._stats_lock:
            busy = self._busy_s
            occw = self._occ_weighted_s
        elapsed = (
            time.time() - self._started_at if self._started_at else 0.0
        )
        busy_frac = busy / elapsed if elapsed > 0 else 0.0
        occ = occw / busy if busy > 0 else 0.0
        return {
            "decode_busy_frac": round(busy_frac, 6),
            "slot_occupancy": round(occ, 6),
            "decode_utilization": round(busy_frac * occ, 6),
        }

    def _paging_snapshot(self) -> Dict[str, Any]:
        """Block-pool / prefix-cache / prefill-backlog state, shared by
        ``stats()``, the Prometheus gauges, and the final ledger row."""
        alloc = self.block_allocator
        total = alloc.num_blocks - 1
        pc = self.prefix_cache
        tier = self._host_tier
        with self._stats_lock:
            backlog = self._backlog_chunks
            jobs = self._prefill_jobs
            parks = self._n_parks
            cow = self._n_cow
            cancelled = self._n_cancelled
            shed = self._n_shed
            spilled = self._n_spilled_blocks
            restored = self._n_restored_blocks
            preloaded = self._kv_preloaded_blocks
            persisted = self._kv_persisted_blocks
            now = time.time()
            pc_rate_window = 0.0
            if pc is not None:
                self._pc_window.observe(pc.hits, pc.hits + pc.misses, now)
                windowed = self._pc_window.ratio(self._stats_window_s, now)
                # Window not yet established (one sample): fall back to
                # the lifetime ratio instead of reporting a false zero.
                pc_rate_window = (
                    round(windowed, 6) if windowed is not None
                    else round(pc.hit_rate, 6)
                )
        return {
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": self.kv_pool_bytes,
            "blocks_total": total,
            "blocks_free": alloc.n_free,
            "block_occupancy": (
                round(alloc.n_used / total, 6) if total else 0.0
            ),
            "prefix_cache_blocks": len(pc) if pc is not None else 0,
            "prefix_cache_hit_rate": (
                round(pc.hit_rate, 6) if pc is not None else 0.0
            ),
            "prefix_cache_hit_rate_window": pc_rate_window,
            "prefix_cache_hits": pc.hits if pc is not None else 0,
            "prefix_cache_misses": pc.misses if pc is not None else 0,
            "prefix_cache_evictions": pc.evictions if pc is not None else 0,
            "prefix_cache_demotions": pc.demotions if pc is not None else 0,
            "prefix_cache_restores": (
                pc.demote_restores if pc is not None else 0
            ),
            "parked_sequences": len(self._parked),
            "requests_shed": shed,
            "kv_offload": self.kv_offload,
            "host_tier_blocks": len(tier) if tier is not None else 0,
            "host_tier_bytes": tier.nbytes if tier is not None else 0,
            "host_spilled_blocks_total": spilled,
            "host_restored_blocks_total": restored,
            "kv_preloaded_blocks": preloaded,
            "kv_persisted_blocks": persisted,
            "prefill_backlog_chunks": backlog,
            "prefill_jobs": jobs,
            "block_parks": parks,
            "cow_copies": cow,
            "requests_cancelled": cancelled,
        }

    def _spec_snapshot(self) -> Dict[str, Any]:
        """Speculative-decoding acceptance state, shared by ``stats()``
        (→ ``/v1/stats``), the gauges, and the final ledger row."""
        with self._stats_lock:
            proposed = self._spec_proposed
            accepted = self._spec_accepted
            fallbacks = self._spec_fallbacks
            steps = self._spec_steps
            now = time.time()
            self._spec_window.observe(accepted, proposed, now)
            windowed = self._spec_window.ratio(self._stats_window_s, now)
        lifetime_rate = round(accepted / proposed, 6) if proposed else 0.0
        return {
            "spec_decode": self.spec_decode,
            "spec_k": self.spec_k,
            "spec_steps": steps,
            "spec_proposed_total": proposed,
            "spec_accepted_total": accepted,
            "spec_fallback_total": fallbacks,
            "spec_accept_rate": lifetime_rate,
            "spec_accept_rate_window": (
                round(windowed, 6) if windowed is not None else lifetime_rate
            ),
        }

    def _ledger_account(self, dt: float, occ_frac: float, tokens: int) -> None:
        """Fold one device-busy interval into the utilization ledger."""
        with self._stats_lock:
            self._busy_s += dt
            self._occ_weighted_s += dt * occ_frac
        led = self._ledger
        if led is None:
            return
        led.account("step_compute_s", dt)
        if tokens:
            led.step(tokens=tokens)
        led.merge_extra(**self._utilization_snapshot())
        led.maybe_flush()

    def stats(self) -> Dict[str, Any]:
        util = self._utilization_snapshot()
        paging = self._paging_snapshot()
        spec = self._spec_snapshot()
        with self._stats_lock:
            now = time.time()
            while self._window and now - self._window[0][0] > 10.0:
                self._window.popleft()
            window_tokens = sum(n for _, n in self._window)
            window_span = (
                now - self._window[0][0] if len(self._window) > 1 else 0.0
            )
            tps = window_tokens / window_span if window_span > 0 else 0.0
            return {
                "state": (
                    "draining"
                    if self._draining
                    else "ready" if self._ready.is_set() else "warming"
                ),
                "warmup": {
                    "done": self._warmup_done,
                    "total": self._warmup_total,
                    "ready_s": round(self._warmup_s, 6),
                },
                "steady_state_compiles": self._n_steady_compiles,
                "slots": self.slots,
                "slots_active": self.allocator.n_active,
                "queue_depth": len(self._queue),
                "requests_submitted": self._n_submitted,
                "requests_finished": self._n_finished,
                "tokens_generated": self._n_tokens,
                "decode_steps": self._n_steps,
                "tokens_per_s": round(tps, 1),
                "max_len": self.max_len,
                "trace_exemplars": self._exemplars.snapshot(),
                **paging,
                **spec,
                **util,
            }

    def latency_summaries(self) -> Dict[str, Dict[str, float]]:
        """Histogram summaries (count/mean/p50/p95/p99) per latency key."""
        summaries_fn = getattr(self.stats_registry, "summaries", None)
        if summaries_fn is None:
            return {}
        wanted = {
            "serving.queue_wait_s": "queue_wait_s",
            "serving.ttft_s": "ttft_s",
            "serving.decode_step_s": "decode_step_s",
            "serving.batch_occupancy": "batch_occupancy",
        }
        out: Dict[str, Dict[str, float]] = {}
        for key, summary in summaries_fn().items():
            if key in wanted:
                out[wanted[key]] = {k: round(v, 6) for k, v in summary.items()}
        return out

    # -- persistent prefix store (warm replica boot) ---------------------------

    @staticmethod
    def _auto_persist_sig(params: Any, qweights: Any, seed: int) -> str:
        """Weight-identity fingerprint for an unsigned persistent store:
        tree structure plus a bounded byte sample (head + tail) of every
        weight leaf — cheap (a few tiny device→host reads) yet it
        changes with the checkpoint, which geometry alone cannot.
        Returns ``""`` when the weights can't be sampled."""
        import hashlib

        import jax

        try:
            h = hashlib.sha256()
            h.update(f"seed:{int(seed)};wq:{qweights is not None};".encode())
            for tree in (params, qweights):
                if tree is None:
                    continue
                leaves, treedef = jax.tree_util.tree_flatten(tree)
                h.update(str(treedef).encode())
                for leaf in leaves:
                    flat = (
                        leaf if hasattr(leaf, "reshape") else np.asarray(leaf)
                    ).reshape(-1)
                    sample = np.concatenate(
                        [
                            np.asarray(jax.device_get(flat[:16])),
                            np.asarray(jax.device_get(flat[-16:])),
                        ]
                    )
                    h.update(str(sample.dtype).encode())
                    h.update(str(flat.shape).encode())
                    h.update(sample.tobytes())
            return "auto:" + h.hexdigest()[:16]
        except Exception:
            return ""

    def _kv_store_meta(self) -> Dict[str, Any]:
        """The compatibility fingerprint a snapshot must match exactly:
        pool geometry + storage dtype (shape compatibility) and the
        caller's model signature (weight identity — geometry alone can't
        tell two checkpoints apart)."""
        c = self.cfg
        return {
            "sig": self.kv_persist_sig,
            "kv_dtype": self.kv_dtype,
            "block_size": self.block_size,
            "n_layers": int(c.n_layers),
            "kv_heads": int(c.kv_heads),
            "head_dim": int(c.head_dim),
            "vocab_size": int(c.vocab_size),
        }

    def persist_prefixes(self) -> int:
        """Snapshot the hottest prefix-cache blocks (chain-closed, see
        ``PrefixCache.hottest_chains``) to ``kv_persist_dir``; returns
        blocks written.  Demoted entries persist straight from their
        host payloads — no device traffic.  Must run on whichever thread
        owns the pool (the scheduler loop, or any thread after join)."""
        pc = self.prefix_cache
        if not self.kv_persist_dir or pc is None:
            return 0
        from polyaxon_tpu.serving import kvstore

        entries = []
        for chain, block, handle in pc.hottest_chains(self.kv_persist_blocks):
            if block >= 0:
                [data] = self._export_blocks([block])
            elif handle is not None and self._host_tier is not None:
                data = self._host_tier.get(handle)
            else:
                continue
            entries.append((chain, data))
        if not entries:
            return 0
        version = kvstore.save_prefix_store(
            self.kv_persist_dir, entries, meta=self._kv_store_meta()
        )
        if version is None:
            return 0
        self._last_persist_t = time.monotonic()
        self._last_persist_mut = pc.mutations
        with self._stats_lock:
            self._kv_persisted_blocks = len(entries)
        return len(entries)

    def _maybe_persist(self, force: bool = False) -> None:
        """Throttled best-effort snapshot: at most one per
        ``POLYAXON_TPU_KV_PERSIST_INTERVAL_S``, and only when the cache
        changed since the last write.  The scheduler calls this from its
        idle branch — incumbents must publish while still RUNNING,
        because scale-up replicas boot exactly when nobody is stopping."""
        pc = self.prefix_cache
        if not self.kv_persist_dir or pc is None or not len(pc):
            return
        # Content churn at constant size (evict+offer of different
        # prefixes, demotions/restores) must re-persist, so freshness
        # keys off the cache's mutation counter, never its len().
        if pc.mutations == self._last_persist_mut:
            return
        if not force:
            now = time.monotonic()
            if now - self._last_persist_t < self._kv_persist_interval_s:
                return
        try:
            self.persist_prefixes()
        except Exception:
            pass

    def _preload_prefixes(self) -> None:
        """Warm boot: hydrate the prefix cache from the newest complete
        snapshot under ``kv_persist_dir`` (scheduler thread, before the
        ready gate).  Loads stop at pool pressure — a preload must never
        starve live admissions of their whole pool."""
        pc = self.prefix_cache
        if not self.kv_persist_dir or pc is None:
            return
        from polyaxon_tpu.serving import kvstore

        loaded = kvstore.load_prefix_store(
            self.kv_persist_dir, expect=self._kv_store_meta()
        )
        if not loaded:
            return
        n = 0
        # Never fill the pool completely: leave at least half for live
        # traffic (preloaded entries are refcount-1 cache entries, so
        # demotion/eviction can reclaim them, but starting gridlocked
        # would stall first admissions behind evictions).
        budget = max(0, (self.block_allocator.num_blocks - 1) // 2)
        for chain, data in loaded:
            if n >= budget:
                break
            block = self.block_allocator.alloc()
            if block is None:
                break
            self._import_block(block, data)
            if not pc.install(chain, block):
                continue
            n += 1
        with self._stats_lock:
            self._kv_preloaded_blocks = n
        # A freshly preloaded cache equals the stored one — don't turn
        # around and persist it right back.
        self._last_persist_mut = pc.mutations
        self._last_persist_t = time.monotonic()

    # -- scheduler loop --------------------------------------------------------

    def _loop(self) -> None:
        tracer = get_tracer()
        self._run_warmup()
        while not self._stop.is_set():
            self._process_cancels()
            self._admit()
            progressed = self._resume_parked()
            # Prefill under a per-iteration TOKEN BUDGET of one chunk:
            # either a single chunk of a long prompt, or several whole
            # short prompts coalesced — a burst of shorts doesn't pay a
            # decode-step round-trip each, while device time between
            # decode steps stays bounded.  Jobs are picked shortest-
            # remaining-work-first: chunk boundaries are preemption
            # points, so a short prompt arriving behind a half-done long
            # one overtakes it instead of waiting out the whole thing.
            # (min() is stable — equal-length jobs stay FIFO.)
            budget = self.prefill_chunk or 0
            spent = 0
            while self._prefill:
                job = min(
                    self._prefill,
                    key=lambda j: len(j.req.prompt) - j.next_pos,
                )
                if job is not self._prefill[0]:
                    self._prefill.remove(job)
                    self._prefill.appendleft(job)
                remaining = len(job.req.prompt) - job.next_pos
                spent += min(remaining, budget) if budget else remaining
                try:
                    # Per-iteration span at the hot sample rate, like the
                    # decode step below: prefill runs per CHUNK.
                    with tracer.span(
                        "serving.prefill",
                        sample=tracer.hot_sample,
                        request_id=job.req.id,
                    ):
                        did = self._prefill_tick()
                except Exception as e:
                    if self._prefill and self._prefill[0] is job:
                        self._prefill.popleft()
                    self._fail_slot(job.slot, f"prefill failed: {e!r}")
                    progressed = True
                    break
                if not did:
                    break  # blocked on the block pool; retry next iteration
                progressed = True
                if not budget or spent >= budget:
                    break
            if self._active.any():
                try:
                    with tracer.span("serving.step", sample=tracer.hot_sample):
                        self._step_once()
                except Exception as e:  # fail in-flight, keep serving
                    for slot in np.nonzero(self._active)[0]:
                        self._fail_slot(int(slot), f"decode step failed: {e!r}")
                continue
            if progressed:
                continue
            if self._parked or self._prefill:
                # Nothing active, nothing moved, eviction already tried:
                # the requests still waiting on blocks are deadlocked —
                # shed one so the rest can make progress.
                self._resolve_block_deadlock()
                continue
            # Fully idle: a good moment to snapshot the prefix store
            # (throttled; scale-up replicas preload whatever incumbents
            # last published).
            self._maybe_persist()
            with self._cv:
                if not self._queue and not self._stop.is_set():
                    self._cv.wait(timeout=0.2)

    def _admit(self) -> None:
        """Move queued requests into free slots (queue order) and enqueue
        their prefill jobs; the prefix cache shortens a job to its first
        uncached block."""
        while True:
            with self._cv:
                if not self._queue:
                    return
                slot = self.allocator.alloc()
                if slot is None:
                    return
                req = self._queue.popleft()
            req.started_at = time.time()
            self.stats_registry.timing(
                "serving.queue_wait_s", req.started_at - req.submitted_at
            )
            self._trace_span(
                req,
                "serving.queue_wait",
                req.submitted_at,
                req.started_at - req.submitted_at,
            )
            self._trace_span(req, "serving.admit", req.started_at, 0.0, slot=slot)
            self._slot_req[slot] = req
            # Speculative path selection is typed per request at
            # admission: greedy requests get a drafter (its suffix index
            # seeded from the prompt here — the prefix-cache path may
            # skip recomputing matched tokens, but the drafter must
            # still see them); sampled requests must see the model's
            # true distribution every step, so they transparently ride
            # single-token rows instead.
            if self.spec_decode:
                if req.temperature > 0:
                    req.spec_mode = SPEC_MODE_FALLBACK_SAMPLED
                    with self._stats_lock:
                        self._spec_fallbacks += 1
                    incr = getattr(self.stats_registry, "incr", None)
                    if incr is not None:
                        incr("serving.spec_fallback_total", 1)
                else:
                    req.spec_mode = SPEC_MODE_GREEDY
                    drafter = NgramDrafter(self.spec_min_ngram)
                    drafter.extend(req.prompt)
                    self._drafters[slot] = drafter
            job = _PrefillJob(req, slot)
            if self.prefix_cache is not None:
                matched = self.prefix_cache.match(req.prompt)
                for i, block in enumerate(matched):
                    self._tables[slot, i] = block
                m = len(matched) * self.block_size
                if matched:
                    self._trace_span(
                        req,
                        "serving.prefix_cache.hit",
                        time.time(),
                        0.0,
                        blocks=len(matched),
                        tokens=m,
                    )
                if m and m == len(req.prompt):
                    # Every prompt block hit.  The last token's LOGITS
                    # still must be recomputed, and its KV row lands in
                    # the final SHARED block — copy it private first
                    # (copy-on-write), then re-run just that one token.
                    job.cow_pending = True
                    job.next_pos = m - 1
                else:
                    job.next_pos = m
            self._prefill.append(job)
            self._record_gauges()

    def _alloc_block(self) -> Optional[int]:
        """Allocate one pool block, evicting a cold cached prefix if the
        free list is empty."""
        block = self.block_allocator.alloc()
        if block is None and self.prefix_cache is not None:
            if self.prefix_cache.evict(1):
                block = self.block_allocator.alloc()
        return block

    def _prefill_tick(self) -> bool:
        """Run ONE chunk of the oldest pending prefill.  Returns True if
        the device did work; False means the job is blocked on the block
        pool (it stays at the head and retries next iteration)."""
        import jax.numpy as jnp

        job = self._prefill[0]
        req, slot = job.req, job.slot
        bs = self.block_size
        t = len(req.prompt)
        t0 = time.perf_counter()
        if job.cow_pending:
            fresh = self._alloc_block()
            if fresh is None:
                return False
            bi = (t - 1) // bs
            shared = int(self._tables[slot, bi])
            self._pool = self._get_copy()(
                self._pool, jnp.int32(shared), jnp.int32(fresh)
            )
            self.block_allocator.decref(shared)
            self._tables[slot, bi] = fresh
            job.cow_pending = False
            with self._stats_lock:
                self._n_cow += 1
        n = t - job.next_pos
        if self.prefill_chunk:
            n = min(n, self.prefill_chunk)
        # Lazy block faults for the chunk's span; partial allocations are
        # kept on exhaustion (the retry only fills what's still unset).
        first_bi = job.next_pos // bs
        last_bi = (job.next_pos + n - 1) // bs
        for bi in range(first_bi, last_bi + 1):
            if self._tables[slot, bi] < 0:
                fresh = self._alloc_block()
                if fresh is None:
                    return False
                self._tables[slot, bi] = fresh
        c_pad = self._bucket(n, self.max_len)
        chunk = np.zeros(c_pad, np.int32)
        chunk[:n] = req.prompt[job.next_pos : job.next_pos + n]
        table = np.where(self._tables[slot] >= 0, self._tables[slot], 0)
        logits, self._pool = self._get_chunk(c_pad)(
            self._params,
            self._pool,
            jnp.asarray(table.astype(np.int32)),
            jnp.asarray(chunk),
            jnp.int32(job.next_pos),
            jnp.int32(n),
        )
        job.next_pos += n
        done = job.next_pos >= t
        if req.trace is not None:
            t1 = time.perf_counter()
            self._trace_span(
                req,
                "serving.prefill.chunk",
                time.time() - (t1 - t0),
                t1 - t0,
                tokens=n,
                pos=job.next_pos,
            )
        # Chunk compute is device-busy time serving one request; only the
        # final chunk emits a token.
        self._ledger_account(
            time.perf_counter() - t0, 1.0 / self.slots,
            tokens=1 if done else 0,
        )
        if done:
            self._prefill.popleft()
            self._finalize_prefill(job, np.asarray(logits))
        self._record_gauges()
        self._progress.beat(step=self._n_steps)
        return True

    def _finalize_prefill(self, job: _PrefillJob, logits: np.ndarray) -> None:
        """Prompt fully inserted: publish its blocks, pick the first
        token from the last chunk's logits, activate the slot."""
        req, slot = job.req, job.slot
        t = len(req.prompt)
        if self.prefix_cache is not None:
            full = t // self.block_size
            self.prefix_cache.offer(
                req.prompt,
                [int(self._tables[slot, i]) for i in range(full)],
            )
        first = self._pick_first(logits, req.temperature)
        # Time-to-first-token: prefill produced it, the client can read it.
        ttft = time.time() - req.submitted_at
        self.stats_registry.timing("serving.ttft_s", ttft)
        if req.trace is not None:
            req.trace.ttft_s = ttft
            self._trace_span(
                req, "serving.first_token", time.time(), 0.0, ttft_s=round(ttft, 6)
            )
        self._emit(slot, req, first)
        if not req.done.is_set():
            self._tok[slot] = first
            self._pos[slot] = t
            self._temps[slot] = req.temperature
            self._active[slot] = True

    def _pick_first(self, logits: np.ndarray, temperature: float) -> int:
        """First generated token comes from the prefill logits (exactly
        like ``generate()``'s post-prefill pick)."""
        if temperature <= 0.0:
            return int(logits.argmax())
        z = logits.astype(np.float64) / temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _park(self, slot: int) -> None:
        """Pool exhausted at a block boundary: deactivate the slot with
        its state intact.  The active mask is data, so parking and
        resuming never recompile.  With the host tier armed, the slot's
        PRIVATE blocks (refcount 1 — shared prefix blocks stay resident,
        they cost the parked slot nothing) spill to pinned host memory
        and their device blocks free: parking RELEASES capacity instead
        of sitting on it, so an oversubscribed pool trades restore
        latency for sheds."""
        self._active[slot] = False
        self._parked.append(slot)
        req = self._slot_req[slot]
        if req is not None and req.trace is not None:
            req.trace.parked_at = time.time()
        with self._stats_lock:
            self._n_parks += 1
        if self._host_tier is not None:
            self._spill_slot(slot)

    def _spill_slot(self, slot: int) -> None:
        """Move a parked slot's private blocks to the host tier (pinned)."""
        alloc = self.block_allocator
        spill_bi: List[int] = []
        for bi in range(self._table_width):
            block = int(self._tables[slot, bi])
            if block >= 0 and alloc.refcount(block) == 1:
                spill_bi.append(bi)
        if not spill_bi:
            return
        payloads = self._export_blocks(
            [int(self._tables[slot, bi]) for bi in spill_bi]
        )
        handles = self._spilled.setdefault(slot, {})
        for bi, data in zip(spill_bi, payloads):
            handles[bi] = self._host_tier.put(data, pinned=True)
            alloc.decref(int(self._tables[slot, bi]))
            self._tables[slot, bi] = -1
        req = self._slot_req[slot]
        if req is not None:
            self._trace_span(
                req, "serving.spill", time.time(), 0.0, blocks=len(spill_bi)
            )
        with self._stats_lock:
            self._n_spilled_blocks += len(spill_bi)

    def _restore_slot(self, slot: int) -> tuple:
        """Stream a parked slot's spilled blocks back (host→device into
        fresh blocks).  ALL-OR-NOTHING: restoration starts only once the
        pool — after demoting/evicting cold prefixes — covers the slot's
        whole remaining need, faulted pos block included.  A partial
        restore would hold device blocks while still parked, and that
        hold-and-wait livelocks against a mid-prefill job holding the
        rest: the restore pass runs FIRST each loop, so it re-grabs
        every block the prefill frees and neither side ever finishes.
        Refusing to start leaves the free list to whoever can actually
        use it.  Returns ``(moved, complete)``."""
        handles = self._spilled.get(slot)
        if not handles:
            return False, True
        need = len(handles)
        bi_pos = int(self._pos[slot]) // self.block_size
        if self._tables[slot, bi_pos] < 0 and bi_pos not in handles:
            need += 1  # the faulted pos block resumes alongside
        alloc = self.block_allocator
        if alloc.n_free < need and self.prefix_cache is not None:
            self.prefix_cache.evict(need - alloc.n_free)
        if alloc.n_free < need:
            return False, False
        n_restore = len(handles)
        t0 = time.perf_counter()
        for bi in sorted(handles):
            fresh = self._alloc_block()
            self._import_block(fresh, self._host_tier.pop(handles.pop(bi)))
            self._tables[slot, bi] = fresh
        self._spilled.pop(slot, None)
        req = self._slot_req[slot]
        if req is not None:
            dt = time.perf_counter() - t0
            self._trace_span(
                req,
                "serving.restore",
                time.time() - dt,
                dt,
                blocks=n_restore,
            )
        return True, True

    def _resume_parked(self) -> bool:
        """Give parked slots another shot at their faulted block, oldest
        first (spilled blocks restore before the fault retries — the
        sequence needs its whole KV back to decode)."""
        progressed = False
        for slot in list(self._parked):
            moved, complete = self._restore_slot(slot)
            if moved:
                progressed = True
            if not complete:
                continue
            bi = int(self._pos[slot]) // self.block_size
            if self._tables[slot, bi] < 0:
                fresh = self._alloc_block()
                if fresh is None:
                    continue
                self._tables[slot, bi] = fresh
            self._unpark(slot)
            self._active[slot] = True
            progressed = True
        return progressed

    def _unpark(self, slot: int) -> None:
        """The ONE bookkeeping site for leaving the parked list — resume,
        retire, and failure all funnel here, so parked-list membership
        and the spill map can never drift apart.  Any payload still
        spilled is discarded (the resume path has already drained its
        map; retire/fail genuinely abandon theirs)."""
        if slot in self._parked:
            self._parked.remove(slot)
            req = self._slot_req[slot]
            rt = req.trace if req is not None else None
            if rt is not None and rt.parked_at is not None:
                parked_s = time.time() - rt.parked_at
                rt.park_s += parked_s
                rt.parked_at = None
                self._trace_span(
                    req, "serving.park", time.time() - parked_s, parked_s
                )
        handles = self._spilled.pop(slot, None)
        if handles and self._host_tier is not None:
            for handle in handles.values():
                self._host_tier.discard(handle)

    def _resolve_block_deadlock(self) -> None:
        """Nobody active, nobody progressing, eviction exhausted: shed
        the newest parked request (it holds blocks, so shedding is
        guaranteed to free some), else the head prefill job.

        Newest-parked is the DELIBERATE victim policy, not an accident
        of list order: the newest parked slot has the least compute
        invested and the oldest has waited longest (parking order is
        arrival order at the wall), so LIFO shedding minimizes wasted
        work while keeping rough arrival fairness for the survivors —
        the same reasoning as classic LIFO preemption under overload.
        With the host tier armed a parked slot has already spilled its
        private blocks, so this path fires only when host+device
        together can't cover the working set (the tier makes sheds
        rare, not cheap).  A FULLY-spilled parked slot holds no device
        blocks at all — shedding it frees nothing — so the victim scan
        prefers parked slots still holding blocks, then the head
        prefill job (whose partial KV is what a true prefill gridlock
        is made of), and only then a spilled slot (unservable: the pool
        can't cover its restore even with everything else idle)."""
        holding = [
            slot
            for slot in self._parked
            if bool((self._tables[slot] >= 0).any())
        ]
        if holding:
            self._fail_slot(
                holding[-1],
                "KV block pool exhausted (request shed)",
                kind="shed",
            )
            return
        if self._prefill:
            job = self._prefill.popleft()
            self._fail_slot(
                job.slot,
                "KV block pool exhausted (request shed)",
                kind="shed",
            )
            return
        if self._parked:
            self._fail_slot(
                self._parked[-1],
                "KV block pool exhausted (request shed)",
                kind="shed",
            )

    def _process_cancels(self) -> None:
        """Apply cancellations to in-flight requests (scheduler thread:
        it owns the tables and allocators)."""
        with self._cv:
            if not self._cancels:
                return
            ids, self._cancels = self._cancels, set()
        for rid in ids:
            for job in list(self._prefill):
                if job.req.id == rid:
                    self._prefill.remove(job)
            for slot, req in enumerate(self._slot_req):
                if req is not None and req.id == rid:
                    self._fail_slot(slot, "request cancelled", kind="cancelled")
                    with self._stats_lock:
                        self._n_cancelled += 1
        self._record_gauges()

    def _step_once(self) -> None:
        import jax
        import jax.numpy as jnp

        bs = self.block_size
        # Block-boundary faults: a slot whose next write crosses into an
        # unallocated block needs one now — or parks until the pool can
        # provide it.
        for slot in np.nonzero(self._active)[0]:
            slot = int(slot)
            bi = int(self._pos[slot]) // bs
            if self._tables[slot, bi] < 0:
                fresh = self._alloc_block()
                if fresh is None:
                    self._park(slot)
                else:
                    self._tables[slot, bi] = fresh
        if not self._active.any():
            return
        drafts = self._collect_drafts() if self.spec_decode else {}
        participants = [
            self._slot_req[int(s)]
            for s in np.nonzero(self._active)[0]
            if self._slot_req[int(s)] is not None
            and self._slot_req[int(s)].trace is not None
        ]
        t0 = time.perf_counter()
        self._key, sub = jax.random.split(self._key)
        tables = np.where(self._tables >= 0, self._tables, 0).astype(np.int32)
        n_live = int(self._active.sum())
        emitted = 0
        if drafts:
            emitted = self._verify_once(drafts, tables, sub)
        else:
            toks, self._pool = self._step_fn(
                self._params,
                self._pool,
                jnp.asarray(tables),
                jnp.asarray(self._tok),
                jnp.asarray(self._pos),
                jnp.asarray(self._active),
                jnp.asarray(self._temps),
                sub,
                self._qweights,
            )
            toks = np.asarray(toks)  # host sync — the loop's one device read
            for slot in np.nonzero(self._active)[0]:
                slot = int(slot)
                req = self._slot_req[slot]
                tok = int(toks[slot])
                self._pos[slot] += 1
                self._tok[slot] = tok
                self._emit(slot, req, tok)
                emitted += 1
        with self._stats_lock:
            self._n_steps += 1
            self._window.append((time.time(), emitted))
        # The step advances every live slot ≥1 token, so its wall time IS
        # the per-token decode latency each of those requests observed
        # (amortized over the accept run on speculative steps).
        step_dt = time.perf_counter() - t0
        self.stats_registry.timing("serving.decode_step_s", step_dt)
        self.stats_registry.observe("serving.batch_occupancy", float(n_live))
        # Per-request decode-step spans ride at the hot-sample rate; the
        # waterfall's decode phase is interval-based, so these are pure
        # detail and sampling them away loses nothing but zoom.
        for req in participants:
            self._trace_hot(
                req,
                "serving.decode.step",
                time.time() - step_dt,
                step_dt,
                batch=n_live,
            )
        self._ledger_account(step_dt, n_live / self.slots, tokens=emitted)
        self._record_gauges()
        if self._ready.is_set():
            self._capture.on_step(self._n_steps)
        self._progress.beat(step=self._n_steps)

    def _collect_drafts(self) -> Dict[int, List[int]]:
        """Ask each active greedy lane's drafter for a proposal, clipped
        to the request's remaining budget (emits = accepts + 1 can never
        overshoot ``max_new_tokens``) and to the KV blocks the pool can
        actually cover — pool pressure degrades a draft to fewer tokens
        (ultimately a plain single-token step) instead of parking."""
        drafts: Dict[int, List[int]] = {}
        bs = self.block_size
        for slot in np.nonzero(self._active)[0]:
            slot = int(slot)
            drafter = self._drafters[slot]
            if drafter is None:
                continue
            req = self._slot_req[slot]
            budget = req.max_new_tokens - len(req.tokens)
            k = min(self.spec_k, budget - 1)
            if k < 1:
                continue
            prop = drafter.draft(k)
            if not prop:
                continue
            # Block faults for the draft span (row j writes pos+j; the
            # pos block was faulted by the caller's boundary loop).
            pos = int(self._pos[slot])
            for j in range(1, len(prop) + 1):
                bi = (pos + j) // bs
                if self._tables[slot, bi] < 0:
                    fresh = self._alloc_block()
                    if fresh is None:
                        prop = prop[: j - 1]
                        break
                    self._tables[slot, bi] = fresh
            if prop:
                drafts[slot] = prop
                self._trace_hot(
                    req, "serving.spec.draft", time.time(), 0.0,
                    proposed=len(prop),
                )
        return drafts

    def _verify_once(
        self, drafts: Dict[int, List[int]], tables: np.ndarray, sub
    ) -> int:
        """One draft→verify→rollback iteration: score every lane's run
        in a single forward pass, append the accepted tokens, truncate
        each table past its rolled-back write position.  Returns tokens
        emitted."""
        import jax.numpy as jnp

        width = self._width_for(max(len(p) for p in drafts.values()))
        tok_in = np.zeros((self.slots, width), np.int32)
        tok_in[:, 0] = self._tok
        n_tok = np.ones(self.slots, np.int32)
        for slot, prop in drafts.items():
            tok_in[slot, 1 : 1 + len(prop)] = prop
            n_tok[slot] = 1 + len(prop)
        out, n_emit, self._pool = self._get_verify(width)(
            self._params,
            self._pool,
            jnp.asarray(tables),
            jnp.asarray(tok_in),
            jnp.asarray(self._pos),
            jnp.asarray(n_tok),
            jnp.asarray(self._active),
            jnp.asarray(self._temps),
            sub,
            self._qweights,
        )
        out = np.asarray(out)  # host sync — the loop's one device read
        n_emit = np.asarray(n_emit)
        emitted = 0
        n_proposed = n_accepted = 0
        observe = getattr(self.stats_registry, "observe", None)
        for slot in np.nonzero(self._active)[0]:
            slot = int(slot)
            req = self._slot_req[slot]
            e = int(n_emit[slot])
            prop = drafts.get(slot)
            if prop is not None:
                n_proposed += len(prop)
                n_accepted += e - 1
                if observe is not None:
                    observe("serving.spec_accept_len", float(e - 1))
                self._trace_hot(
                    req,
                    "serving.spec.verify",
                    time.time(),
                    0.0,
                    proposed=len(prop),
                    accepted=e - 1,
                )
            self._pos[slot] += e
            self._tok[slot] = int(out[slot, e - 1])
            # Rollback: rows past the accept run are garbage; blocks
            # wholly beyond the next write position go back to the pool.
            truncate_table(
                self._tables[slot],
                self.block_allocator,
                int(self._pos[slot]),
                self.block_size,
            )
            for j in range(e):
                self._emit(slot, req, int(out[slot, j]))
                emitted += 1
                if req.done.is_set():
                    break  # eos/budget retired the slot mid-run
        with self._stats_lock:
            self._spec_steps += 1
            self._spec_proposed += n_proposed
            self._spec_accepted += n_accepted
        incr = getattr(self.stats_registry, "incr", None)
        if incr is not None:
            if n_proposed:
                incr("serving.spec_proposed_total", n_proposed)
            if n_accepted:
                incr("serving.spec_accepted_total", n_accepted)
        return emitted

    def _record_gauges(self) -> None:
        """Refresh paging gauges + backlog counters (scheduler thread)."""
        self._check_steady_compiles()
        backlog = 0
        for job in self._prefill:
            remaining = len(job.req.prompt) - job.next_pos
            step = self.prefill_chunk or max(remaining, 1)
            backlog += max(1, -(-remaining // step))
        with self._stats_lock:
            self._backlog_chunks = backlog
            self._prefill_jobs = len(self._prefill)
        gauge = getattr(self.stats_registry, "gauge", None)
        if gauge is None:
            return
        alloc = self.block_allocator
        total = alloc.num_blocks - 1
        gauge(
            "serving.block_occupancy",
            round(alloc.n_used / total, 6) if total else 0.0,
        )
        gauge("serving.blocks_free", float(alloc.n_free))
        gauge("serving.kv_pool_bytes", float(self.kv_pool_bytes))
        pc = self.prefix_cache
        gauge(
            "serving.prefix_cache_hit_rate",
            round(pc.hit_rate, 6) if pc is not None else 0.0,
        )
        gauge("serving.prefill_backlog_chunks", float(backlog))
        gauge("serving.parked_sequences", float(len(self._parked)))
        if pc is not None:
            gauge("serving.prefix_cache_evictions", float(pc.evictions))
            gauge("serving.prefix_cache_demotions", float(pc.demotions))
            gauge("serving.prefix_cache_restores", float(pc.demote_restores))
        if self._host_tier is not None:
            gauge("serving.host_tier_blocks", float(len(self._host_tier)))
            gauge("serving.host_tier_bytes", float(self._host_tier.nbytes))
        if self.spec_decode:
            with self._stats_lock:
                proposed, accepted = self._spec_proposed, self._spec_accepted
            gauge(
                "serving.spec_accept_rate",
                round(accepted / proposed, 6) if proposed else 0.0,
            )

    # -- request-scoped tracing ------------------------------------------------

    def _trace_span(
        self,
        req: GenerationRequest,
        name: str,
        start: float,
        duration: float,
        **attrs: Any,
    ) -> None:
        """Record one phase span under the request's trace (no-op for
        untraced requests)."""
        rt = req.trace
        if rt is None:
            return
        get_tracer().record_span(
            name,
            start=start,
            duration=duration,
            trace_id=rt.ctx.trace_id,
            parent_id=rt.root_id,
            request_id=req.id,
            **attrs,
        )

    def _trace_hot(
        self,
        req: GenerationRequest,
        name: str,
        start: float,
        duration: float,
        **attrs: Any,
    ) -> None:
        """Hot-path phase span (per decode step / spec verify): recorded
        at the tracer's hot-sample rate.  Waterfall phase accounting is
        interval-based and never depends on these, so sampling them away
        cannot break the waterfall sums."""
        rt = req.trace
        if rt is None:
            return
        rate = get_tracer().hot_sample
        if rate < 1.0 and (rate <= 0.0 or random.random() >= rate):
            return
        self._trace_span(req, name, start, duration, **attrs)

    def _finalize_trace(self, req: GenerationRequest, outcome: str) -> None:
        """Close the request's trace: emit the root span, build the
        latency waterfall, and offer it to the slow-request exemplars.

        Runs for every terminal path — finish, shed, cancel, engine
        stop, deadlock shed — so a traced request can never leak an
        open span."""
        rt = req.trace
        if rt is None or req.trace_summary is not None:
            return
        now = req.finished_at if req.finished_at is not None else time.time()
        req.finished_at = now
        if rt.parked_at is not None:  # failed while parked
            rt.park_s += now - rt.parked_at
            rt.parked_at = None
        total = max(0.0, now - req.submitted_at)
        started = req.started_at
        first = req.first_token_at
        waterfall: Dict[str, float] = {
            "queue_wait_s": max(
                0.0, (started if started is not None else now) - req.submitted_at
            ),
        }
        if started is not None:
            prefill_end = first if first is not None else now
            waterfall["prefill_s"] = max(0.0, prefill_end - started)
        if first is not None:
            waterfall["decode_s"] = max(0.0, now - first - rt.park_s)
        if rt.park_s > 0:
            waterfall["parked_s"] = rt.park_s
        # The request root span: its id is what every phase span parents
        # to; its own parent is the remote caller's span (router attempt
        # or lm_server handler), stitching the cross-process timeline.
        get_tracer().record_span(
            "serving.request",
            start=req.submitted_at,
            duration=total,
            trace_id=rt.ctx.trace_id,
            span_id=rt.root_id,
            parent_id=rt.ctx.span_id or None,
            request_id=req.id,
            outcome=outcome,
            tokens=len(req.tokens),
        )
        self._trace_span(
            req, "serving.finish", now, 0.0, outcome=outcome
        )
        req.trace_summary = {
            "trace_id": rt.ctx.trace_id,
            "span_id": rt.root_id,
            "request_id": req.id,
            "outcome": outcome,
            "total_s": round(total, 6),
            "ttft_s": (
                round(rt.ttft_s, 6) if rt.ttft_s is not None else None
            ),
            "tokens": len(req.tokens),
            "finished_at": now,
            "waterfall": {k: round(v, 6) for k, v in waterfall.items()},
        }
        self._exemplars.offer(req.trace_summary)

    def _emit(self, slot: int, req: GenerationRequest, tok: int) -> None:
        """Record one generated token; retire the slot when done."""
        if req.first_token_at is None:
            req.first_token_at = time.time()
        drafter = self._drafters[slot]
        if drafter is not None:
            drafter.append(tok)  # accepted tokens extend the suffix index
        req.tokens.append(tok)
        req.stream.put(tok)
        with self._stats_lock:
            self._n_tokens += 1
        hit_eos = self.eos_id is not None and tok == self.eos_id
        if len(req.tokens) >= req.max_new_tokens or hit_eos:
            self._retire(slot, req)

    def _release_slot_blocks(self, slot: int) -> None:
        """Drop the slot's reference on every block in its table.  Blocks
        a neighbor or the prefix cache still references stay allocated —
        the defining safety property of sharing."""
        for bi in range(self._table_width):
            block = int(self._tables[slot, bi])
            if block >= 0:
                self.block_allocator.decref(block)
        self._tables[slot, :] = -1

    def _retire(self, slot: int, req: GenerationRequest) -> None:
        req.finished_at = time.time()
        self._active[slot] = False
        self._unpark(slot)
        self._finalize_trace(req, "completed")
        req.stream.put(None)
        req.done.set()
        self._release_slot_blocks(slot)
        self._slot_req[slot] = None
        self._drafters[slot] = None
        self.allocator.free(slot)
        with self._stats_lock:
            self._n_finished += 1
        # Waiters in submit-order take freed slots on the NEXT admit —
        # i.e. immediately, mid-flight of every other slot.
        with self._cv:
            self._cv.notify_all()

    def _fail_slot(self, slot: int, msg: str, kind: Optional[str] = None) -> None:
        req = self._slot_req[slot]
        self._active[slot] = False
        self._unpark(slot)
        self._release_slot_blocks(slot)
        self._slot_req[slot] = None
        self._drafters[slot] = None
        self.allocator.free(slot)
        if kind == "shed":
            with self._stats_lock:
                self._n_shed += 1
        if req is not None and not req.done.is_set():
            req.error = msg
            req.error_kind = kind
            self._finalize_trace(req, kind or "error")
            req.stream.put(None)
            req.done.set()
