"""Continuous-batching generation engine over a slot-addressed KV cache.

Iteration-level scheduling (Orca; the KV management popularized by
vLLM, here slot-granular rather than paged): the engine owns one
``[L, slots, max_len, Hkv, d]`` cache and ONE jitted
:func:`~polyaxon_tpu.models.decode.slot_decode_step` whose shapes depend
only on the slot count — per-slot positions, the active mask, and the
slot index of every admission are DATA, so steady-state serving never
recompiles.  Each scheduler iteration:

1. **admit** — while a slot is free and the queue is non-empty, prefill
   the next prompt (one B=1 forward, padded to a small bucket set so
   prompt lengths don't mint unbounded compilations) and write its KV
   into the free slot via ``insert_prompt``;
2. **step** — one batched decode step advances every active slot one
   token, each at its own position;
3. **retire** — finished slots (max_new reached, or EOS) are freed
   IMMEDIATELY; the next queued request takes the slot on the very next
   iteration, while its neighbors keep decoding.

Tokens stream back per-request as they land (``GenerationRequest.stream``);
a request's latency is its own prefill + its own tokens, not the
longest neighbor's.  Greedy outputs are token-identical to sequential
:func:`~polyaxon_tpu.models.decode.generate` calls
(tests/test_serving/test_engine.py asserts it per request).

Sharded + quantized serving compose exactly like the request-granular
path did: place the params (and the int8 ``(q, scale)`` tree) with
``decode_param_shardings`` / ``quantized_weight_shardings`` and GSPMD
propagates head-sharding through prefill and the slot step — the KV
slots live on the gang mesh.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from polyaxon_tpu.stats import MemoryStats
from polyaxon_tpu.tracking.flightrec import get_progress
from polyaxon_tpu.tracking.trace import get_tracer


class GenerationRequest:
    """One queued generation: its prompt, its budget, and its results.

    ``stream`` yields token ids as they are generated (a ``None``
    sentinel marks completion); ``done`` is set when the request has
    finished (or failed — see ``error``).  ``tokens`` accumulates the
    generated ids in order.
    """

    _ids = itertools.count()

    def __init__(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
    ) -> None:
        self.id = next(self._ids)
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.tokens: List[int] = []
        self.stream: "queue.Queue[Optional[int]]" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Block until done; raise on engine-side failure."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens


class SlotAllocator:
    """FIFO free-list over ``n`` cache slots.

    Freed slots go to the BACK of the list, so reuse order is the order
    slots were released — the coldest slot is reused first, which keeps
    any one slot's stale KV rows short-lived (and makes the admit/evict/
    reuse sequence deterministic for tests).
    """

    def __init__(self, n: int) -> None:
        if n < 1:
            raise ValueError(f"need at least one slot, got {n}")
        self.n = n
        self._free: deque = deque(range(n))
        self._held: set = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        slot = self._free.popleft()
        self._held.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._held:
            raise ValueError(f"slot {slot} is not allocated")
        self._held.discard(slot)
        self._free.append(slot)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return len(self._held)


class ServingEngine:
    """The continuous-batching scheduler: one thread owns the device.

    Parameters
    ----------
    params, cfg : the model (a ``TransformerConfig`` tree).
    slots : concurrent sequences the cache holds (the static batch dim).
    max_len : per-slot sequence capacity (default ``cfg.max_seq``).
    qweights : int8 tree from ``decode.quantize_weights`` — the slot
        step streams int8 exactly like request-granular decode did.
    mesh / param_shardings / qweights_shardings : multi-chip serving;
        when given, params (and qweights) are placed on the mesh and
        GSPMD propagates the sharding through prefill and the step.
    eos_id : optional token id that retires a slot early.
    seed : RNG seed for the sampling path (greedy ignores it).
    stats : a stats backend receiving latency histograms
        (``serving.queue_wait_s`` / ``serving.ttft_s`` /
        ``serving.decode_step_s`` / ``serving.batch_occupancy``);
        defaults to a private :class:`MemoryStats` — ``lm_server`` passes
        the process-wide registry so ``/metrics`` exports them.
    """

    #: Prompt-length padding buckets: powers of two bound the number of
    #: prefill compilations at log2(max_len) regardless of traffic.
    @staticmethod
    def _bucket(t: int, max_len: int) -> int:
        b = 8
        while b < t:
            b *= 2
        return min(b, max_len)

    def __init__(
        self,
        params: Any,
        cfg: Any,
        *,
        slots: int = 4,
        max_len: Optional[int] = None,
        qweights: Optional[Any] = None,
        mesh: Any = None,
        param_shardings: Optional[Any] = None,
        qweights_shardings: Optional[Any] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        stats: Optional[Any] = None,
    ) -> None:
        import jax

        from polyaxon_tpu.models import decode

        if max_len is None:
            max_len = cfg.max_seq
        if max_len > cfg.max_seq:
            raise ValueError(
                f"max_len ({max_len}) exceeds the model's max_seq "
                f"({cfg.max_seq})"
            )
        self.cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self._mesh = mesh
        if param_shardings is not None:
            params = jax.device_put(params, param_shardings)
        if qweights is not None and qweights_shardings is not None:
            qweights = jax.device_put(qweights, qweights_shardings)
        self._params = params
        self._qweights = qweights
        self._cache = decode.init_cache(cfg, self.slots, self.max_len)

        # Host-side per-slot state: the NEXT token to feed, its absolute
        # position, the active mask, and each slot's sampling temperature.
        self._tok = np.zeros(self.slots, np.int32)
        self._pos = np.zeros(self.slots, np.int32)
        self._active = np.zeros(self.slots, bool)
        self._temps = np.zeros(self.slots, np.float32)
        self._slot_req: List[Optional[GenerationRequest]] = [None] * self.slots

        self.allocator = SlotAllocator(self.slots)
        self._queue: "deque[GenerationRequest]" = deque()
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._key = jax.random.PRNGKey(seed)
        self._rng = np.random.default_rng(seed)
        self._prefill_fns: Dict[int, Any] = {}
        self._insert_fns: Dict[int, Any] = {}
        self._step_fn = self._build_step()

        # Stats: lifetime counters plus a sliding window for tokens/s;
        # latency distributions go to the (possibly shared) histogram
        # registry so /metrics can export percentiles.
        self.stats_registry = stats if stats is not None else MemoryStats()
        # Decode ticks feed the process's stall watchdog: a serving worker
        # that stops emitting tokens is as stuck as a hung train step.
        self._progress = get_progress()
        self._stats_lock = threading.Lock()
        self._n_submitted = 0
        self._n_finished = 0
        self._n_tokens = 0
        self._n_steps = 0
        self._window: "deque[tuple]" = deque()  # (t, n_tokens)
        # Decode-side utilization ledger (armed in start()): device-busy
        # seconds (prefill + decode dispatch/sync) and occupancy-weighted
        # busy time — the serving analogue of train-side goodput/MFU.
        self._ledger: Optional[Any] = None
        self._started_at: Optional[float] = None
        self._busy_s = 0.0
        self._occ_weighted_s = 0.0

    # -- compiled functions ----------------------------------------------------

    def _donate(self) -> tuple:
        # Cache donation halves peak HBM for the engine's largest buffer;
        # CPU ignores donation with a warning, so only request it on
        # accelerator backends.
        import jax

        return (1,) if jax.default_backend() != "cpu" else ()

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.decode import slot_decode_step

        cfg = self.cfg

        def step(params, cache, tokens, pos, active, temps, key, qweights):
            logits, cache = slot_decode_step(
                params, cache, tokens, pos, active, cfg, qweights=qweights
            )
            greedy_tok = jnp.argmax(logits, axis=-1)
            # Per-slot keys: a slot's sample must not depend on which
            # neighbors happen to be in flight.
            keys = jax.random.split(key, logits.shape[0])
            safe = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(jax.random.categorical)(
                keys, logits / safe[:, None]
            )
            tok = jnp.where(temps > 0, sampled, greedy_tok)
            return jnp.where(active, tok, 0).astype(jnp.int32), cache

        return jax.jit(step, donate_argnums=self._donate())

    def _get_prefill(self, t_pad: int):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.transformer import forward

        if t_pad not in self._prefill_fns:
            cfg = self.cfg

            def pre(params, tokens, last):
                logits, (k, v) = forward(params, tokens, cfg, return_kv=True)
                # Right-padded prompt: the real last-token logits sit at
                # index ``last`` (causal attention keeps them independent
                # of the pad tail).
                return jnp.take(logits[0], last, axis=0), k[:, 0], v[:, 0]

            self._prefill_fns[t_pad] = jax.jit(pre)
        return self._prefill_fns[t_pad]

    def _get_insert(self, t_pad: int):
        import jax

        from polyaxon_tpu.models.decode import insert_prompt

        if t_pad not in self._insert_fns:
            self._insert_fns[t_pad] = jax.jit(
                lambda cache, slot, k, v: insert_prompt(cache, slot, k, v),
                donate_argnums=(0,) if self._donate() else (),
            )
        return self._insert_fns[t_pad]

    # -- public API ------------------------------------------------------------

    def start(self) -> "ServingEngine":
        if self._thread is None:
            from polyaxon_tpu.tracking.ledger import get_ledger

            self._ledger = get_ledger().start(source="serving")
            self._started_at = time.time()
            self._thread = threading.Thread(
                target=self._loop, name="serving-engine", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._ledger is not None:
            self._ledger.merge_extra(**self._utilization_snapshot())
            self._ledger.flush(final=True)
            self._ledger = None
        # Fail anything still queued or in flight so waiters unblock.
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending + [r for r in self._slot_req if r is not None]:
            if not req.done.is_set():
                req.error = "engine stopped"
                req.stream.put(None)
                req.done.set()

    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
    ) -> GenerationRequest:
        """Validate and enqueue; returns immediately with the request."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if any(t < 0 or t >= self.cfg.vocab_size for t in prompt):
            raise ValueError("token id out of vocabulary range")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be positive")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len})"
            )
        req = GenerationRequest(prompt, max_new_tokens, temperature)
        with self._cv:
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            self._queue.append(req)
            self._n_submitted += 1
            self._cv.notify_all()
        return req

    def generate(
        self,
        prompt: List[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> List[int]:
        """Blocking convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens, temperature).wait(timeout)

    def _utilization_snapshot(self) -> Dict[str, float]:
        """Decode-side utilization: busy fraction of wall clock since
        start(), mean slot occupancy while busy, and their product — the
        serving equivalent of the train ledger's goodput × MFU."""
        with self._stats_lock:
            busy = self._busy_s
            occw = self._occ_weighted_s
        elapsed = (
            time.time() - self._started_at if self._started_at else 0.0
        )
        busy_frac = busy / elapsed if elapsed > 0 else 0.0
        occ = occw / busy if busy > 0 else 0.0
        return {
            "decode_busy_frac": round(busy_frac, 6),
            "slot_occupancy": round(occ, 6),
            "decode_utilization": round(busy_frac * occ, 6),
        }

    def _ledger_account(self, dt: float, occ_frac: float, tokens: int) -> None:
        """Fold one device-busy interval into the utilization ledger."""
        with self._stats_lock:
            self._busy_s += dt
            self._occ_weighted_s += dt * occ_frac
        led = self._ledger
        if led is None:
            return
        led.account("step_compute_s", dt)
        if tokens:
            led.step(tokens=tokens)
        led.merge_extra(**self._utilization_snapshot())
        led.maybe_flush()

    def stats(self) -> Dict[str, Any]:
        util = self._utilization_snapshot()
        with self._stats_lock:
            now = time.time()
            while self._window and now - self._window[0][0] > 10.0:
                self._window.popleft()
            window_tokens = sum(n for _, n in self._window)
            window_span = (
                now - self._window[0][0] if len(self._window) > 1 else 0.0
            )
            tps = window_tokens / window_span if window_span > 0 else 0.0
            return {
                "slots": self.slots,
                "slots_active": self.allocator.n_active,
                "queue_depth": len(self._queue),
                "requests_submitted": self._n_submitted,
                "requests_finished": self._n_finished,
                "tokens_generated": self._n_tokens,
                "decode_steps": self._n_steps,
                "tokens_per_s": round(tps, 1),
                "max_len": self.max_len,
                **util,
            }

    def latency_summaries(self) -> Dict[str, Dict[str, float]]:
        """Histogram summaries (count/mean/p50/p95/p99) per latency key."""
        summaries_fn = getattr(self.stats_registry, "summaries", None)
        if summaries_fn is None:
            return {}
        wanted = {
            "serving.queue_wait_s": "queue_wait_s",
            "serving.ttft_s": "ttft_s",
            "serving.decode_step_s": "decode_step_s",
            "serving.batch_occupancy": "batch_occupancy",
        }
        out: Dict[str, Dict[str, float]] = {}
        for key, summary in summaries_fn().items():
            if key in wanted:
                out[wanted[key]] = {k: round(v, 6) for k, v in summary.items()}
        return out

    # -- scheduler loop --------------------------------------------------------

    def _loop(self) -> None:
        tracer = get_tracer()
        while not self._stop.is_set():
            self._admit()
            if not self._active.any():
                with self._cv:
                    if not self._queue and not self._stop.is_set():
                        self._cv.wait(timeout=0.2)
                continue
            try:
                # Per-iteration span, sampled at the hot rate: the decode
                # loop runs per generated token, full tracing would cost
                # more than the histograms it duplicates.
                with tracer.span("serving:step", sample=tracer.hot_sample):
                    self._step_once()
            except Exception as e:  # fail in-flight requests, keep serving
                for slot in np.nonzero(self._active)[0]:
                    self._fail_slot(int(slot), f"decode step failed: {e!r}")

    def _admit(self) -> None:
        """Prefill waiting requests into free slots (queue order)."""
        while True:
            with self._cv:
                if not self._queue:
                    return
                slot = self.allocator.alloc()
                if slot is None:
                    return
                req = self._queue.popleft()
            try:
                tracer = get_tracer()
                with tracer.span(
                    "serving:admit", sample=tracer.hot_sample, request_id=req.id
                ):
                    self._prefill_into(slot, req)
            except Exception as e:
                self._slot_req[slot] = None
                self.allocator.free(slot)
                req.error = f"prefill failed: {e!r}"
                req.stream.put(None)
                req.done.set()

    def _prefill_into(self, slot: int, req: GenerationRequest) -> None:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        req.started_at = time.time()
        self.stats_registry.timing(
            "serving.queue_wait_s", req.started_at - req.submitted_at
        )
        t = len(req.prompt)
        t_pad = self._bucket(t, self.max_len)
        padded = np.zeros((1, t_pad), np.int32)
        padded[0, :t] = req.prompt
        last_logits, k, v = self._get_prefill(t_pad)(
            self._params, jnp.asarray(padded), jnp.int32(t - 1)
        )
        self._cache = self._get_insert(t_pad)(
            self._cache, jnp.int32(slot), k, v
        )
        first = self._pick_first(np.asarray(last_logits), req.temperature)
        # Time-to-first-token: prefill produced it, the client can read it.
        self.stats_registry.timing("serving.ttft_s", time.time() - req.submitted_at)
        self._slot_req[slot] = req
        self._emit(slot, req, first)
        if not req.done.is_set():
            self._tok[slot] = first
            self._pos[slot] = t
            self._temps[slot] = req.temperature
            self._active[slot] = True
        # Prefill is device-busy time serving one request (+ its first
        # emitted token).
        self._ledger_account(
            time.perf_counter() - t0, 1.0 / self.slots, tokens=1
        )

    def _pick_first(self, logits: np.ndarray, temperature: float) -> int:
        """First generated token comes from the prefill logits (exactly
        like ``generate()``'s post-prefill pick)."""
        if temperature <= 0.0:
            return int(logits.argmax())
        z = logits.astype(np.float64) / temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _step_once(self) -> None:
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        self._key, sub = jax.random.split(self._key)
        toks, self._cache = self._step_fn(
            self._params,
            self._cache,
            jnp.asarray(self._tok),
            jnp.asarray(self._pos),
            jnp.asarray(self._active),
            jnp.asarray(self._temps),
            sub,
            self._qweights,
        )
        toks = np.asarray(toks)  # host sync — the loop's one device read
        n_live = int(self._active.sum())
        for slot in np.nonzero(self._active)[0]:
            slot = int(slot)
            req = self._slot_req[slot]
            tok = int(toks[slot])
            self._pos[slot] += 1
            self._tok[slot] = tok
            self._emit(slot, req, tok)
        with self._stats_lock:
            self._n_steps += 1
            self._window.append((time.time(), n_live))
        # The step advances every live slot one token, so its wall time IS
        # the per-token decode latency each of those requests observed.
        step_dt = time.perf_counter() - t0
        self.stats_registry.timing("serving.decode_step_s", step_dt)
        self.stats_registry.observe("serving.batch_occupancy", float(n_live))
        self._ledger_account(step_dt, n_live / self.slots, tokens=n_live)
        self._progress.beat(step=self._n_steps)

    def _emit(self, slot: int, req: GenerationRequest, tok: int) -> None:
        """Record one generated token; retire the slot when done."""
        req.tokens.append(tok)
        req.stream.put(tok)
        with self._stats_lock:
            self._n_tokens += 1
        hit_eos = self.eos_id is not None and tok == self.eos_id
        if len(req.tokens) >= req.max_new_tokens or hit_eos:
            self._retire(slot, req)

    def _retire(self, slot: int, req: GenerationRequest) -> None:
        req.finished_at = time.time()
        req.stream.put(None)
        req.done.set()
        self._active[slot] = False
        self._slot_req[slot] = None
        self.allocator.free(slot)
        with self._stats_lock:
            self._n_finished += 1
        # Waiters in submit-order take freed slots on the NEXT admit —
        # i.e. immediately, mid-flight of every other slot.
        with self._cv:
            self._cv.notify_all()

    def _fail_slot(self, slot: int, msg: str) -> None:
        req = self._slot_req[slot]
        self._active[slot] = False
        self._slot_req[slot] = None
        self.allocator.free(slot)
        if req is not None and not req.done.is_set():
            req.error = msg
            req.stream.put(None)
            req.done.set()
