"""Standalone ``lm_server`` replica: the fleet's subprocess entrypoint.

``python -m polyaxon_tpu.serving.replica <spec.json>`` boots one
engine + the production HTTP handler (``_make_lm_handler``) with no
platform Context — the process-level unit
:class:`~polyaxon_tpu.serving.fleet.LocalServingFleet` provisions via
``spawner.transport.LocalExecTransport`` so fault injection (SIGKILL /
SIGSTOP) hits a real OS process, not a thread.

The spec is plain JSON::

    {
      "host": "127.0.0.1", "port": 8301, "seed": 0,
      "model": {"vocab_size": 64, "d_model": 32, ...},  # TransformerConfig ints
      "seq": 48, "slots": 4, "block_size": 16,
      "kv_blocks": null, "prefill_chunk": 0,
      "kv_offload": false, "kv_offload_blocks": 0,
      "kv_persist_dir": null, "kv_persist_sig": "",
      "max_new_tokens": 64, "request_timeout_s": 600.0,
      "retry_after_s": 1.0
    }

Random-init weights only (the fleet bench/test double); checkpointed
fleets go through the control-plane path (``orchestrator`` +
``builtins.services.lm_server``), which this entry deliberately does
not duplicate.
"""

from __future__ import annotations

import json
import sys


def serve(spec: dict) -> None:
    # Heavy imports stay inside serve() so `--help`-style failures and
    # spec parse errors don't pay for jax.
    import os

    import jax

    from polyaxon_tpu.builtins.services import _make_lm_handler
    from polyaxon_tpu.models import TransformerConfig, init_params
    from polyaxon_tpu.serving import ServingEngine
    from polyaxon_tpu.tracking.trace import get_tracer

    # Label this process's spans with the replica name: span ids become
    # globally unique across the fleet and the router's merged trace
    # export gives each replica its own named Perfetto track.
    name = str(spec.get("name") or f"replica-{spec.get('port', 0)}")
    get_tracer().configure(process=name, process_id=os.getpid())

    model = {k: int(v) for k, v in (spec.get("model") or {}).items()}
    seq = int(spec.get("seq", 128))
    cfg = TransformerConfig(max_seq=seq, **model)
    params = init_params(jax.random.PRNGKey(int(spec.get("seed", 0))), cfg)

    kv_blocks = spec.get("kv_blocks")
    prefill_chunk = int(spec.get("prefill_chunk", 0) or 0)
    spec_decode = spec.get("spec_decode")
    spec_k = spec.get("spec_k")
    spec_min_ngram = spec.get("spec_min_ngram")
    kv_offload = spec.get("kv_offload")
    kv_offload_blocks = spec.get("kv_offload_blocks")
    kv_persist_dir = spec.get("kv_persist_dir")
    engine = ServingEngine(
        params,
        cfg,
        slots=int(spec.get("slots", 4)),
        max_len=seq,
        block_size=int(spec.get("block_size", 16)),
        num_blocks=int(kv_blocks) if kv_blocks is not None else None,
        prefill_chunk=prefill_chunk if prefill_chunk > 0 else None,
        seed=int(spec.get("seed", 0)),
        spec_decode=bool(spec_decode) if spec_decode is not None else None,
        spec_k=int(spec_k) if spec_k is not None else None,
        spec_min_ngram=(
            int(spec_min_ngram) if spec_min_ngram is not None else None
        ),
        kv_offload=bool(kv_offload) if kv_offload is not None else None,
        kv_offload_blocks=(
            int(kv_offload_blocks) if kv_offload_blocks is not None else None
        ),
        kv_persist_dir=str(kv_persist_dir) if kv_persist_dir else None,
        kv_persist_sig=str(spec.get("kv_persist_sig", "")),
    ).start()

    meta = {
        "checkpoint_step": None,
        "target": None,
        "default_max_new": int(spec.get("max_new_tokens", 64)),
        "request_timeout_s": float(spec.get("request_timeout_s", 600.0)),
        "retry_after_s": float(spec.get("retry_after_s", 1.0)),
    }
    from http.server import ThreadingHTTPServer

    handler = _make_lm_handler(engine, cfg, meta)
    host = str(spec.get("host", "127.0.0.1"))
    port = int(spec["port"])
    server = ThreadingHTTPServer((host, port), handler)
    print(f"replica: serving on {host}:{port}", flush=True)
    try:
        server.serve_forever()
    finally:
        engine.stop()


def main(argv) -> int:
    if len(argv) != 1:
        print("usage: python -m polyaxon_tpu.serving.replica <spec.json>")
        return 2
    with open(argv[0]) as f:
        spec = json.load(f)
    serve(spec)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
