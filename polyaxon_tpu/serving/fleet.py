"""Serving fleets: N ``lm_server`` replicas behind a :class:`FleetRouter`.

Two provisioning layers share the router:

- :class:`LocalServingFleet` — replicas as REAL subprocesses via
  ``spawner.transport.LocalExecTransport`` (the same primitive gang
  spawners build on).  This is the fault-injection harness: SIGKILL
  kills a replica mid-request (failover path), SIGSTOP freezes one
  without closing its sockets (stall/eviction path).  Used by the
  ``serving_fleet_*`` benches and the router integration tests.
- :class:`ServingFleet` — replicas as control-plane ``kind: service``
  runs (full registry lifecycle: heartbeats, alerts, command bus).
  The fleet registers with the :class:`RemediationEngine`; a firing
  ``serving_ttft_p99`` / ``heartbeat_stale`` alert on a replica run
  becomes a drain→replace operation whose phases are visible on the
  run's remediation timeline:

  ``draining``   router stops routing; a ``drain`` bus command flips the
                 engine to 503-draining; in-flight requests finish,
                 bounded by ``POLYAXON_TPU_FLEET_DRAIN_DEADLINE_S``;
  ``replacing``  old run stopped, replacement run submitted;
  ``succeeded``  replacement probed ``ready`` — routing resumed;
  ``failed``     replacement missed ``POLYAXON_TPU_FLEET_READY_TIMEOUT_S``.

:class:`ServingFleet` is deliberately thread-free: ``poll()`` advances
everything and is driven by whoever owns the orchestrator's pump loop,
so fleet state never races the scheduler.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import sys
import time
from pathlib import Path
from typing import Any, Dict, Optional

from polyaxon_tpu.conf.knobs import knob_float, knob_int
from polyaxon_tpu.serving.router import FleetRouter, _http_json
from polyaxon_tpu.stats.metrics import labeled_key

__all__ = ["LocalServingFleet", "ServingFleet"]

#: Shared phase key with the scheduler's monitor-tick breakdown — the
#: autoscaler pump is one more control-plane phase on the same histogram.
_AUTOSCALER_PHASE_KEY = labeled_key("tick_phase_s", phase="autoscaler")


def _observe_autoscaler_phase(router: Any, seconds: float) -> None:
    try:
        router.metrics.observe(_AUTOSCALER_PHASE_KEY, seconds)
    except Exception:  # pragma: no cover - stats must never raise
        pass


class LocalServingFleet:
    """Subprocess replicas on this machine + a router fronting them.

    ``model`` is the ``TransformerConfig`` int-field dict each replica
    builds (random init, fixed ``seed`` — every replica serves identical
    weights, so greedy failover replays are token-identical).
    """

    def __init__(
        self,
        workdir: Path,
        model: Dict[str, int],
        *,
        replicas: Optional[int] = None,
        seq: int = 128,
        slots: int = 4,
        block_size: int = 16,
        kv_blocks: Optional[int] = None,
        seed: int = 0,
        spec_decode: Optional[bool] = None,
        spec_k: Optional[int] = None,
        spec_min_ngram: Optional[int] = None,
        kv_offload: Optional[bool] = None,
        kv_offload_blocks: Optional[int] = None,
        kv_persist_dir: Optional[str] = None,
        kv_persist_sig: str = "",
        request_timeout_s: float = 600.0,
        host: str = "127.0.0.1",
        router: Optional[FleetRouter] = None,
        env: Optional[Dict[str, str]] = None,
    ) -> None:
        from polyaxon_tpu.spawner.transport import LocalExecTransport

        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.model = dict(model)
        self.replicas = (
            replicas
            if replicas is not None
            else knob_int("POLYAXON_TPU_FLEET_REPLICAS")
        )
        self.seq = seq
        self.slots = slots
        self.block_size = block_size
        self.kv_blocks = kv_blocks
        self.seed = seed
        # Speculative decoding rides the replica spec (None = the
        # replica's own POLYAXON_TPU_SERVING_SPEC_* knob defaults).
        self.spec_decode = spec_decode
        self.spec_k = spec_k
        self.spec_min_ngram = spec_min_ngram
        # KV hierarchy rides the spec too: every replica (including
        # autoscaler scale-ups, which re-enter launch_replica) shares
        # one kv_persist_dir, so a new replica boots prefix-warm from
        # whatever the incumbents last persisted.
        self.kv_offload = kv_offload
        self.kv_offload_blocks = kv_offload_blocks
        self.kv_persist_dir = kv_persist_dir
        self.kv_persist_sig = kv_persist_sig
        self.request_timeout_s = request_timeout_s
        self.host = host
        self.env = dict(env or {})
        self.transport = LocalExecTransport()
        self.router = router if router is not None else FleetRouter()
        self._procs: Dict[str, Any] = {}
        self._counter = itertools.count()
        self.autoscaler: Optional[Any] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "LocalServingFleet":
        for _ in range(self.replicas):
            self.launch_replica()
        self.router.start()
        return self

    def launch_replica(self, name: Optional[str] = None) -> str:
        from polyaxon_tpu.spawner.local import _free_port

        name = name or f"r{next(self._counter)}"
        port = _free_port()
        spec = {
            "name": name,
            "host": self.host,
            "port": port,
            "seed": self.seed,
            "model": self.model,
            "seq": self.seq,
            "slots": self.slots,
            "block_size": self.block_size,
            "kv_blocks": self.kv_blocks,
            "spec_decode": self.spec_decode,
            "spec_k": self.spec_k,
            "spec_min_ngram": self.spec_min_ngram,
            "kv_offload": self.kv_offload,
            "kv_offload_blocks": self.kv_offload_blocks,
            "kv_persist_dir": self.kv_persist_dir,
            "kv_persist_sig": self.kv_persist_sig,
            "request_timeout_s": self.request_timeout_s,
        }
        spec_path = self.workdir / f"{name}.json"
        spec_path.write_text(json.dumps(spec))
        # The replica runs with cwd=workdir, so an uninstalled (source
        # checkout) polyaxon_tpu must ride on PYTHONPATH explicitly.
        import polyaxon_tpu

        pkg_root = str(Path(polyaxon_tpu.__file__).resolve().parent.parent)
        existing = os.environ.get("PYTHONPATH")
        env = dict(self.env)
        env.setdefault(
            "PYTHONPATH",
            pkg_root + (os.pathsep + existing if existing else ""),
        )
        ref = self.transport.launch(
            "localhost",
            [sys.executable, "-m", "polyaxon_tpu.serving.replica", str(spec_path)],
            env,
            cwd=str(self.workdir),
            log_path=self.workdir / f"{name}.log",
            rc_path=self.workdir / f"{name}.rc",
        )
        self._procs[name] = ref
        self.router.add_replica(name, f"http://{self.host}:{port}")
        return name

    def wait_ready(
        self, n: Optional[int] = None, timeout_s: Optional[float] = None
    ) -> bool:
        """Block until ``n`` replicas probe ``ready`` (default: all)."""
        n = n if n is not None else len(self._procs)
        timeout_s = (
            timeout_s
            if timeout_s is not None
            else knob_float("POLYAXON_TPU_FLEET_READY_TIMEOUT_S")
        )
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            self.router.probe_all()
            if self.router.stats()["n_ready"] >= n:
                return True
            time.sleep(0.2)
        return False

    def stop(self) -> None:
        self.router.stop()
        for ref in self._procs.values():
            ref.signal(signal.SIGKILL)
        for ref in self._procs.values():
            ref.wait(timeout=10)
        self._procs.clear()

    # -- fault injection -------------------------------------------------------
    def kill_replica(self, name: str) -> None:
        """SIGKILL: sockets die mid-request — the failover path."""
        self._procs[name].signal(signal.SIGKILL)

    def stall_replica(self, name: str) -> None:
        """SIGSTOP: the process freezes with sockets OPEN — probes time
        out instead of failing fast, the ejection path's worst case."""
        self._procs[name].signal(signal.SIGSTOP)

    def resume_replica(self, name: str) -> None:
        self._procs[name].signal(signal.SIGCONT)

    def replace_replica(self, name: str) -> str:
        """Kill ``name`` (if alive), drop it from routing, launch a
        fresh replica — the local analogue of drain-and-replace."""
        self.retire_replica(name)
        return self.launch_replica()

    def chaos_target(self) -> Optional[str]:
        """Deterministic victim for an untargeted chaos event: the
        first (by name) ready replica the router still routes to."""
        ready = sorted(
            n
            for n in self.router.replica_names()
            if (r := self.router.replica(n)) is not None
            and r.state == "ready"
            and n in self._procs
        )
        return ready[0] if ready else None

    # -- resize protocol (FleetAutoscaler) -------------------------------------
    def scale_up(self) -> str:
        return self.launch_replica()

    def retire_replica(self, name: str) -> None:
        ref = self._procs.pop(name, None)
        if ref is not None:
            ref.signal(signal.SIGKILL)
            ref.wait(timeout=10)
        self.router.remove_replica(name)

    def run_id_for(self, name: str) -> Optional[int]:
        return None  # subprocess replicas have no registry run

    def attach_autoscaler(self, **kwargs: Any) -> Any:
        from polyaxon_tpu.serving.autoscaler import FleetAutoscaler

        self.autoscaler = FleetAutoscaler(self, **kwargs)
        return self.autoscaler

    def poll(self) -> None:
        """Thread-free pump (mirrors :meth:`ServingFleet.poll`): reap
        replicas whose subprocess died out from under us (a SIGKILLed
        corpse would otherwise sit ejected forever, pinning autoscaler
        membership at a capacity the router cannot route to), probe
        when no router thread owns it, then tick the autoscaler."""
        for name, ref in list(self._procs.items()):
            if ref.poll() is not None:
                self.retire_replica(name)
        if getattr(self.router, "_thread", None) is None:
            self.router.probe_all()
        if self.autoscaler is not None:
            t0 = time.perf_counter()
            try:
                self.autoscaler.evaluate()
            finally:
                _observe_autoscaler_phase(
                    self.router, time.perf_counter() - t0
                )


class ServingFleet:
    """Control-plane fleet: replicas are ``kind: service`` registry runs.

    ``declarations`` are the per-replica run declarations (model shape,
    ``slots``, ``seq``, optionally ``target`` for checkpointed weights);
    ``environment`` the topology block (defaults to ``cpu-1``).

    Drive with ``poll()`` from the pump loop.  It (1) registers replica
    ``service_url``s on the router as gangs come up, (2) probes when no
    router thread is running, and (3) advances drain→replace operations
    opened by :meth:`request_drain_replace` (the remediation engine's
    entry point).
    """

    ACTION = "drain_replace"

    def __init__(
        self,
        orch: Any,
        *,
        name: str = "fleet",
        declarations: Optional[Dict[str, Any]] = None,
        environment: Optional[Dict[str, Any]] = None,
        replicas: Optional[int] = None,
        drain_deadline_s: Optional[float] = None,
        ready_timeout_s: Optional[float] = None,
        router: Optional[FleetRouter] = None,
    ) -> None:
        self.orch = orch
        self.name = name
        self.declarations = dict(declarations or {})
        self.environment = environment or {
            "topology": {"accelerator": "cpu-1", "num_devices": 1, "num_hosts": 1}
        }
        self.replicas = (
            replicas
            if replicas is not None
            else knob_int("POLYAXON_TPU_FLEET_REPLICAS")
        )
        self.drain_deadline_s = (
            drain_deadline_s
            if drain_deadline_s is not None
            else knob_float("POLYAXON_TPU_FLEET_DRAIN_DEADLINE_S")
        )
        self.ready_timeout_s = (
            ready_timeout_s
            if ready_timeout_s is not None
            else knob_float("POLYAXON_TPU_FLEET_READY_TIMEOUT_S")
        )
        self.router = router if router is not None else FleetRouter()
        self.autoscaler: Optional[Any] = None
        #: replica name → registry run id (current membership).
        self._runs: Dict[str, int] = {}
        #: old run id → in-flight drain/replace operation state.
        self._ops: Dict[int, Dict[str, Any]] = {}
        #: replica name → ``finished_at`` of the newest slow-request
        #: exemplar already landed as a ``ttft_slow`` anomaly row.
        self._exemplar_seen: Dict[str, float] = {}
        self._exemplar_harvest_at = 0.0
        self._counter = itertools.count()
        fleets = getattr(orch, "fleets", None)
        if fleets is not None:
            fleets.append(self)
        remediation = getattr(orch, "remediation", None)
        if remediation is not None and hasattr(remediation, "register_fleet"):
            remediation.register_fleet(self)

    # -- membership ------------------------------------------------------------
    def start(self) -> "ServingFleet":
        for _ in range(self.replicas):
            self._submit_replica()
        return self

    def _submit_replica(self) -> str:
        name = f"{self.name}-r{next(self._counter)}"
        run = self.orch.submit(
            {
                "kind": "service",
                "declarations": dict(self.declarations),
                "environment": dict(self.environment),
            },
            name=name,
        )
        self._runs[name] = run.id
        return name

    def run_ids(self) -> Dict[str, int]:
        return dict(self._runs)

    def handles_run(self, run_id: int) -> bool:
        return run_id in self._runs.values()

    def _name_for(self, run_id: int) -> Optional[str]:
        for name, rid in self._runs.items():
            if rid == run_id:
                return name
        return None

    # -- resize protocol (FleetAutoscaler) -------------------------------------
    def scale_up(self) -> str:
        return self._submit_replica()

    def retire_replica(self, name: str) -> None:
        run_id = self._runs.pop(name, None)
        if run_id is not None:
            try:
                self.orch.stop_run(run_id, actor="autoscaler")
            except Exception:
                pass
        self.router.remove_replica(name)

    def run_id_for(self, name: str) -> Optional[int]:
        return self._runs.get(name)

    def attach_autoscaler(self, **kwargs: Any) -> Any:
        from polyaxon_tpu.serving.autoscaler import FleetAutoscaler

        self.autoscaler = FleetAutoscaler(self, **kwargs)
        return self.autoscaler

    # -- remediation entry point -----------------------------------------------
    def request_drain_replace(
        self, run_id: int, rem_id: int, rule: str
    ) -> bool:
        """Open a drain→replace operation on a replica run (called by
        the remediation engine on a firing alert edge).  Synchronous
        part is flag-flips only; the heavy lifting happens in
        :meth:`poll`."""
        name = self._name_for(run_id)
        if name is None or run_id in self._ops:
            return False
        self._ops[run_id] = {
            "name": name,
            "rem_id": rem_id,
            "rule": rule,
            "phase": "draining",
            "deadline": time.time() + self.drain_deadline_s,
        }
        # Best-effort: the engine 503s new admissions while it finishes
        # in-flight work.  A wedged/dead replica never acks — the router
        # drain deadline covers that.
        try:
            self.orch.send_command(
                run_id, "drain", payload={"rule": rule}, actor="remediation"
            )
        except Exception:
            pass
        self.router.drain(name, deadline_s=self.drain_deadline_s)
        return True

    #: Seconds between exemplar-harvest sweeps — a /v1/stats fetch per
    #: replica, so it must not ride every 50 ms pump tick.
    EXEMPLAR_HARVEST_INTERVAL_S = 2.0

    # -- pump ------------------------------------------------------------------
    def poll(self) -> None:
        self._register_urls()
        if getattr(self.router, "_thread", None) is None:
            self.router.probe_all()
        now = time.time()
        self._harvest_exemplars(now)
        for run_id in list(self._ops):
            op = self._ops[run_id]
            if op["phase"] == "draining":
                self._poll_draining(run_id, op, now)
            elif op["phase"] == "replacing":
                self._poll_replacing(run_id, op, now)
        if self.autoscaler is not None:
            t0 = time.perf_counter()
            try:
                self.autoscaler.evaluate(now)
            finally:
                _observe_autoscaler_phase(
                    self.router, time.perf_counter() - t0
                )

    def _register_urls(self) -> None:
        for name, run_id in list(self._runs.items()):
            if self.router.replica(name) is not None:
                continue
            try:
                run = self.orch.get_run(run_id)
            except Exception:
                continue
            if run.service_url:
                self.router.add_replica(name, run.service_url)

    def _harvest_exemplars(self, now: float) -> None:
        """Land each replica's slow-request exemplars as ``ttft_slow``
        anomaly rows + a run-artifact JSON dump.

        The engine keeps a bounded ring of the slowest fully-traced
        requests per window (``trace_exemplars`` on ``/v1/stats``); the
        control plane copies anything newer than the last sweep into the
        replica run's ``reports/`` dir and records the run-relative key
        on the anomaly row — exactly the flight-recorder ``stall``
        contract, so a firing ``serving_ttft_p99`` alert attaches it via
        ``RuleContext.dump_artifact("ttft_slow")``.
        """
        if now - self._exemplar_harvest_at < self.EXEMPLAR_HARVEST_INTERVAL_S:
            return
        self._exemplar_harvest_at = now
        registry = getattr(self.orch, "registry", None)
        layout = getattr(self.orch, "layout", None)
        if registry is None or layout is None:
            return
        for name, run_id in list(self._runs.items()):
            rep = self.router.replica(name)
            if rep is None or rep.state not in ("ready", "draining"):
                continue
            try:
                code, body = _http_json(
                    rep.base_url + "/v1/stats",
                    timeout=self.router.probe_timeout_s,
                )
            except Exception:
                continue
            if code != 200:
                continue
            exemplars = body.get("trace_exemplars") or []
            newest = max(
                (float(e.get("finished_at") or 0.0) for e in exemplars),
                default=0.0,
            )
            if not exemplars or newest <= self._exemplar_seen.get(name, 0.0):
                continue
            try:
                run = self.orch.get_run(run_id)
                paths = layout.run_paths(run.uuid)
                paths.reports.mkdir(parents=True, exist_ok=True)
                fname = f"ttft_exemplars_{int(newest * 1000)}.json"
                (paths.reports / fname).write_text(
                    json.dumps(
                        {"replica": name, "exemplars": exemplars}, indent=2
                    )
                )
                registry.add_anomaly(
                    run_id,
                    "ttft_slow",
                    message=(
                        f"{len(exemplars)} slow-request exemplar(s) "
                        f"from {name}"
                    ),
                    attrs={
                        "dump_artifact": f"reports/{fname}",
                        "trace_ids": [
                            e.get("trace_id") for e in exemplars
                        ],
                    },
                )
            except Exception:
                continue
            self._exemplar_seen[name] = newest

    def _poll_draining(
        self, run_id: int, op: Dict[str, Any], now: float
    ) -> None:
        name = op["name"]
        rep = self.router.replica(name)
        drained = rep is None or rep.state in ("drained",)
        if not drained and now < op["deadline"]:
            return
        # Drained (or deadline): stop the old run, cut it from routing,
        # and bring up the replacement.
        try:
            self.orch.stop_run(run_id, actor="remediation")
        except Exception:
            pass
        self.router.remove_replica(name)
        self._runs.pop(name, None)
        replacement = self._submit_replica()
        op["phase"] = "replacing"
        op["replacement"] = replacement
        op["deadline"] = now + self.ready_timeout_s
        self._update_rem(
            op,
            attrs={
                "phase": "replacing",
                "replacement": replacement,
                "replacement_run_id": self._runs[replacement],
                "drain_timed_out": not drained,
            },
            message=f"drained {name}; replacing with {replacement}",
        )

    def _poll_replacing(
        self, run_id: int, op: Dict[str, Any], now: float
    ) -> None:
        from polyaxon_tpu.db.registry import RemediationStatus

        rep = self.router.replica(op.get("replacement", ""))
        if rep is not None and rep.state == "ready":
            self._update_rem(
                op,
                status=RemediationStatus.SUCCEEDED,
                attrs={"phase": "done"},
                message=(
                    f"replacement {op['replacement']} ready — routing resumed"
                ),
            )
            self._ops.pop(run_id, None)
            return
        if now >= op["deadline"]:
            self._update_rem(
                op,
                status=RemediationStatus.FAILED,
                attrs={"phase": "failed"},
                message=(
                    f"replacement {op.get('replacement')} missed the "
                    f"{self.ready_timeout_s:.0f}s ready deadline"
                ),
            )
            self._ops.pop(run_id, None)

    def _update_rem(self, op: Dict[str, Any], **kwargs: Any) -> None:
        try:
            self.orch.registry.update_remediation(op["rem_id"], **kwargs)
        except Exception:
            pass

    # -- introspection ---------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        st = self.router.stats()
        return {
            "name": self.name,
            "replicas": {
                name: {"run_id": rid} for name, rid in self._runs.items()
            },
            "router": st,
            "open_ops": {
                rid: {k: v for k, v in op.items() if k != "deadline"}
                for rid, op in self._ops.items()
            },
            "autoscaler": (
                self.autoscaler.status() if self.autoscaler is not None else None
            ),
        }

    def stop(self) -> None:
        self.router.stop()
        remediation = getattr(self.orch, "remediation", None)
        if remediation is not None and hasattr(remediation, "unregister_fleet"):
            remediation.unregister_fleet(self)
        fleets = getattr(self.orch, "fleets", None)
        if fleets is not None and self in fleets:
            fleets.remove(self)
