"""Poisson-arrival load harness for the serving engine.

The existing ``serving_tokens_per_s`` bench number compares sequential
vs concurrent submission of the SAME instant — it says nothing about
tail latency under sustained load.  This harness drives the engine the
way traffic actually arrives: exponential inter-arrival gaps at a
target rate, one watcher thread per request reading its token STREAM
(so TTFT is measured at the moment the first token is readable by a
client, not when ``wait()`` returns), and aggregate tokens/s over the
loaded wall clock.

The interesting output is ``ttft_p99_s``: with full-prompt prefill a
request that arrives behind a long prompt waits the WHOLE prefill
before its own; with chunked prefill it waits at most one chunk —
bench.py runs this harness twice at the same offered load and schedule
to show exactly that difference.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(np.ceil(q / 100.0 * len(sorted_vals))) - 1)
    return sorted_vals[max(idx, 0)]


def poisson_load(
    engine: Any,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int,
    *,
    rate_rps: float,
    temperature: float = 0.0,
    seed: int = 0,
    timeout_s: float = 600.0,
) -> Dict[str, Any]:
    """Offer ``prompts`` to a RUNNING engine at ``rate_rps`` Poisson
    arrivals; returns loaded-throughput and TTFT-percentile metrics.

    The arrival schedule is drawn up front from ``seed``, so two runs
    with the same (prompts, rate, seed) offer the identical load — the
    property that makes chunked-vs-full prefill A/B comparisons fair.
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=len(prompts))

    results: List[Optional[tuple]] = [None] * len(prompts)

    def watch(i: int, req: Any, t_submit: float) -> None:
        ttft = None
        n_tokens = 0
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                tok = req.stream.get(timeout=remaining)
            except Exception:
                break
            if tok is None:
                break
            if ttft is None:
                ttft = time.perf_counter() - t_submit
            n_tokens += 1
        results[i] = (
            ttft,
            n_tokens,
            time.perf_counter() - t_submit,
            req.error,
            getattr(req, "error_kind", None),
        )

    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    for i, prompt in enumerate(prompts):
        time.sleep(float(gaps[i]))
        t_submit = time.perf_counter()
        req = engine.submit(list(prompt), max_new_tokens, temperature)
        th = threading.Thread(
            target=watch, args=(i, req, t_submit), daemon=True
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start

    done = [r for r in results if r is not None]
    ttfts = sorted(r[0] for r in done if r[0] is not None)
    total_tokens = sum(r[1] for r in done)
    completed = sum(
        1 for r in done if r[3] is None and r[1] >= max_new_tokens
    )
    # A shed (engine refusing work it cannot fit) is LOAD SIGNAL, not a
    # fault: count it apart from errors so an A/B at fixed offered load
    # can't trade sheds for "failures" and call it even.
    sheds = sum(1 for r in done if r[4] == "shed")
    errors = sum(1 for r in done if r[3] is not None and r[4] != "shed")
    return {
        "n_requests": len(prompts),
        "completed": completed,
        "sheds": sheds,
        "errors": errors,
        "offered_rps": round(float(rate_rps), 4),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall > 0 else 0.0,
        "total_tokens": total_tokens,
        "ttft_mean_s": (
            round(float(np.mean(ttfts)), 6) if ttfts else 0.0
        ),
        "ttft_p50_s": round(_pct(ttfts, 50), 6),
        "ttft_p95_s": round(_pct(ttfts, 95), 6),
        "ttft_p99_s": round(_pct(ttfts, 99), 6),
        # Per-request TTFT by submission index (None = no first token),
        # so callers can compute percentiles over request CLASSES —
        # e.g. interactive shorts vs batch longs, which chunked prefill
        # deliberately trades against each other.
        "ttft_s": [
            (round(r[0], 6) if r is not None and r[0] is not None else None)
            for r in results
        ],
    }


def shared_prefix_prompts(
    n: int,
    vocab_size: int,
    *,
    prefix_len: int,
    suffix_len: int,
    groups: int = 4,
    seed: int = 0,
) -> List[List[int]]:
    """``n`` prompts in ``groups`` families sharing a common prefix —
    the traffic class prefix-affinity routing exists for.

    Every prompt in a family starts with the family's ``prefix_len``
    tokens (drawn once) followed by a private ``suffix_len`` suffix.
    Fully determined by ``seed``, so a fleet A/B offers the identical
    byte-for-byte prompt set to both arms.
    """
    if n <= 0 or groups <= 0:
        raise ValueError(f"need n > 0 and groups > 0, got n={n} groups={groups}")
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, vocab_size, size=prefix_len).tolist()
        for _ in range(groups)
    ]
    prompts = []
    for i in range(n):
        suffix = rng.integers(0, vocab_size, size=suffix_len).tolist()
        prompts.append(prefixes[i % groups] + suffix)
    return prompts


def templated_prompts(
    n: int,
    vocab_size: int,
    *,
    n_templates: int = 4,
    header_len: int = 16,
    motif_len: int = 4,
    rows: int = 4,
    field_len: int = 2,
    seed: int = 0,
) -> List[List[int]]:
    """``n`` prompts from ``n_templates`` template families with high
    n-gram SELF-overlap — the traffic class speculative decoding's
    prompt-lookup drafter wins on.

    Each family fixes a ``header_len``-token header (shared across the
    family, so prefix caching composes) and a ``motif_len``-token record
    motif; each prompt is the header followed by ``rows`` records of
    ``motif + private fields`` (``field_len`` tokens drawn per prompt).
    The motif recurring every record gives the drafter's suffix index
    repeated n-grams to match mid-generation, the way real templated
    traffic (forms, logs, structured extraction) repeats boilerplate.
    Fully determined by ``seed`` — an A/B offers byte-identical prompts
    to both arms.
    """
    if n <= 0 or n_templates <= 0:
        raise ValueError(
            f"need n > 0 and n_templates > 0, got n={n} "
            f"n_templates={n_templates}"
        )
    rng = np.random.default_rng(seed)
    templates = [
        (
            rng.integers(0, vocab_size, size=header_len).tolist(),
            rng.integers(0, vocab_size, size=motif_len).tolist(),
        )
        for _ in range(n_templates)
    ]
    prompts = []
    for i in range(n):
        header, motif = templates[i % n_templates]
        body: List[int] = []
        for _ in range(rows):
            body += motif
            body += rng.integers(0, vocab_size, size=field_len).tolist()
        prompts.append(header + body)
    return prompts


def _fire_one(
    base: str,
    prompt: Sequence[int],
    max_new_tokens: int,
    temperature: float,
    timeout_s: float,
    t_submit: float,
) -> "tuple[str, Optional[float], int, Optional[Dict[str, Any]]]":
    """One ``/generate`` round-trip → (typed outcome, ttft, n_tokens,
    trace block).

    The typed-outcome contract shared by every HTTP load harness:
    ``completed`` / ``shed`` (429) / ``error:<kind>`` /
    ``failure:<ExcType>`` — exactly one outcome per request.  The trace
    block is the server's ``{"trace_id", "waterfalls"}`` response key
    (None when tracing is off or the request failed).
    """
    import json as json_mod
    import urllib.error
    import urllib.request

    payload = json_mod.dumps(
        {
            "prompts": [list(prompt)],
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
        }
    ).encode()
    req = urllib.request.Request(
        base + "/generate",
        data=payload,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = json_mod.loads(resp.read() or b"{}")
        n_tok = sum(len(t) for t in body.get("tokens") or [])
        server_ttfts = [
            t for t in (body.get("ttft_s") or []) if t is not None
        ]
        # Client-observed TTFT = queueing delay to the server plus
        # the server-side first-token latency it reports.
        ttft = (
            min(server_ttfts) if server_ttfts
            else time.perf_counter() - t_submit
        )
        trace = body.get("trace")
        return "completed", ttft, n_tok, (
            trace if isinstance(trace, dict) else None
        )
    except urllib.error.HTTPError as e:
        try:
            err = (json_mod.loads(e.read() or b"{}").get("error")) or {}
        except ValueError:
            err = {}
        kind = str(err.get("kind") or f"http_{e.code}")
        return ("shed" if e.code == 429 else f"error:{kind}"), None, 0, None
    except Exception as e:
        return f"failure:{type(e).__name__}", None, 0, None


def http_poisson_load(
    base_url: str,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int,
    *,
    rate_rps: float,
    temperature: float = 0.0,
    seed: int = 0,
    timeout_s: float = 600.0,
    kill_at_s: Optional[Dict[str, float]] = None,
    stall_at_s: Optional[Dict[str, float]] = None,
    fleet: Any = None,
) -> Dict[str, Any]:
    """Poisson load over HTTP against a router or a single ``lm_server``.

    The fleet analogue of :func:`poisson_load`, plus a seeded FAULT
    SCHEDULE: ``kill_at_s`` / ``stall_at_s`` map replica name → seconds
    after load start at which ``fleet.kill_replica`` /
    ``fleet.stall_replica`` fires — so "one replica dies mid-load" is a
    reproducible bench arm, not a flaky race.

    Per-request outcomes are typed, mirroring the router's error model:

    - ``completed`` — HTTP 200, all tokens;
    - ``shed`` — typed 429 (engine pool or router occupancy ceiling);
    - ``error:<kind>`` — any other typed HTTP error (exactly one per
      request — the zero-silent-drops contract);
    - ``failure`` — connection-level failure reaching the endpoint;
    - ``hang`` — no outcome within ``timeout_s`` (must be ZERO — a hang
      means a request was silently dropped).
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=len(prompts))
    base = base_url.rstrip("/")

    outcomes: List[Optional[str]] = [None] * len(prompts)
    ttfts_by_idx: List[Optional[float]] = [None] * len(prompts)
    latencies: List[Optional[float]] = [None] * len(prompts)
    tokens_out = [0] * len(prompts)
    traces: List[Optional[Dict[str, Any]]] = [None] * len(prompts)

    def fire(i: int, prompt: Sequence[int], t_submit: float) -> None:
        outcome, ttft, n_tok, trace = _fire_one(
            base, prompt, max_new_tokens, temperature, timeout_s, t_submit
        )
        tokens_out[i] = n_tok
        ttfts_by_idx[i] = ttft
        outcomes[i] = outcome
        traces[i] = trace
        latencies[i] = time.perf_counter() - t_submit

    # Fault schedule: one timer thread per event, armed relative to load
    # start so the schedule is part of the (seeded) experiment.
    timers: List[threading.Timer] = []
    for name, at_s in (kill_at_s or {}).items():
        timers.append(
            threading.Timer(float(at_s), fleet.kill_replica, args=(name,))
        )
    for name, at_s in (stall_at_s or {}).items():
        timers.append(
            threading.Timer(float(at_s), fleet.stall_replica, args=(name,))
        )

    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    for t in timers:
        t.daemon = True
        t.start()
    try:
        for i, prompt in enumerate(prompts):
            time.sleep(float(gaps[i]))
            th = threading.Thread(
                target=fire,
                args=(i, prompt, time.perf_counter()),
                daemon=True,
            )
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=timeout_s)
    finally:
        for t in timers:
            t.cancel()
    wall = time.perf_counter() - t_start

    hangs = sum(1 for th in threads if th.is_alive())
    completed = sum(1 for o in outcomes if o == "completed")
    sheds = sum(1 for o in outcomes if o == "shed")
    errors = sum(1 for o in outcomes if o and o.startswith("error:"))
    failures = sum(1 for o in outcomes if o and o.startswith("failure:"))
    total_tokens = sum(tokens_out)
    ttfts = sorted(t for t in ttfts_by_idx if t is not None)
    return {
        "n_requests": len(prompts),
        "completed": completed,
        "sheds": sheds,
        "errors": errors,
        "failures": failures,
        "hangs": hangs,
        "offered_rps": round(float(rate_rps), 4),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall > 0 else 0.0,
        "total_tokens": total_tokens,
        "ttft_mean_s": round(float(np.mean(ttfts)), 6) if ttfts else 0.0,
        "ttft_p50_s": round(_pct(ttfts, 50), 6),
        "ttft_p95_s": round(_pct(ttfts, 95), 6),
        "ttft_p99_s": round(_pct(ttfts, 99), 6),
        "ttft_s": [
            round(t, 6) if t is not None else None for t in ttfts_by_idx
        ],
        "outcomes": list(outcomes),
        "trace_ids": [
            t.get("trace_id") if t is not None else None for t in traces
        ],
        "slow_requests": _slowest_traced(traces, latencies, n=5),
    }


def _slowest_traced(
    traces: "List[Optional[Dict[str, Any]]]",
    latencies: "List[Optional[float]]",
    *,
    n: int,
) -> List[Dict[str, Any]]:
    """The ``n`` slowest traced requests (by client-observed latency)
    with their server waterfalls — the load summary's "where did the
    tail go" exhibit.  Empty when the server traced nothing."""
    slow = []
    for trace, latency in zip(traces, latencies):
        if trace is None or latency is None:
            continue
        waterfalls = trace.get("waterfalls") or [None]
        slow.append(
            {
                "trace_id": trace.get("trace_id"),
                "request_id": (waterfalls[0] or {}).get("request_id"),
                "latency_s": round(latency, 6),
                "waterfall": (waterfalls[0] or {}).get("waterfall"),
            }
        )
    slow.sort(key=lambda e: e["latency_s"], reverse=True)
    return slow[:n]


class ChaosEvent:
    """One scheduled fault/traffic event on the chaos timeline.

    ``at_s`` seconds after load start, ``action`` one of:

    - ``kill`` — SIGKILL ``target`` (or the fleet's deterministic
      default victim) mid-whatever-it-was-doing;
    - ``stall`` — SIGSTOP: freeze with sockets open;
    - ``resume`` — SIGCONT a stalled replica (``target`` required);
    - ``burst`` — ``n`` extra back-to-back arrivals on top of the
      phase schedule (traffic chaos, not process chaos).
    """

    ACTIONS = ("kill", "stall", "resume", "burst")

    def __init__(
        self,
        at_s: float,
        action: str,
        *,
        target: Optional[str] = None,
        n: int = 0,
    ) -> None:
        if action not in self.ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}")
        if action == "resume" and target is None:
            raise ValueError("resume requires an explicit target")
        if action == "burst" and n <= 0:
            raise ValueError("burst requires n > 0")
        self.at_s = float(at_s)
        self.action = action
        self.target = target
        self.n = int(n)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"ChaosEvent({self.at_s}, {self.action!r}, "
            f"target={self.target!r}, n={self.n})"
        )


def chaos_schedule(
    phases: Sequence["tuple[float, float]"],
    *,
    seed: int = 0,
    events: Sequence[ChaosEvent] = (),
) -> "List[tuple[float, int]]":
    """Expand a phased-rate schedule + burst events into the exact
    arrival timeline: a sorted list of ``(at_s, phase_idx)``.

    ``phases`` is ``[(duration_s, rate_rps), ...]``; within each phase
    arrivals are Poisson at that rate (rate 0 = idle phase, no
    arrivals), drawn entirely from ``seed`` — same (phases, seed,
    events) ⇒ byte-identical offered load, the property every chaos
    A/B leans on.  ``burst`` events inject ``n`` simultaneous arrivals
    at ``at_s``, tagged with the phase containing them.
    """
    rng = np.random.default_rng(seed)
    arrivals: List["tuple[float, int]"] = []
    t0 = 0.0
    bounds: List["tuple[float, float]"] = []
    for idx, (duration_s, rate_rps) in enumerate(phases):
        if duration_s <= 0:
            raise ValueError(f"phase {idx}: duration must be > 0")
        bounds.append((t0, t0 + duration_s))
        if rate_rps > 0:
            t = t0
            while True:
                t += float(rng.exponential(1.0 / rate_rps))
                if t >= t0 + duration_s:
                    break
                arrivals.append((t, idx))
        t0 += duration_s
    for ev in events:
        if ev.action != "burst":
            continue
        idx = next(
            (i for i, (lo, hi) in enumerate(bounds) if lo <= ev.at_s < hi),
            max(0, len(bounds) - 1),
        )
        arrivals.extend((ev.at_s, idx) for _ in range(ev.n))
    arrivals.sort()
    return arrivals


def chaos_poisson_load(
    base_url: str,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int,
    *,
    phases: Sequence["tuple[float, float]"],
    seed: int = 0,
    events: Sequence[ChaosEvent] = (),
    fleet: Any = None,
    pump: Any = None,
    pump_interval_s: float = 0.05,
    temperature: float = 0.0,
    timeout_s: float = 600.0,
) -> Dict[str, Any]:
    """Phased Poisson load composed with a seeded chaos timeline.

    The autoscaler's proving ground: ``phases`` shapes offered load
    over time (ramp → sustain → idle), ``events`` injects
    kill/stall/resume/burst chaos at fixed offsets, and ``pump`` (e.g.
    ``fleet.poll``) is called every ``pump_interval_s`` for the whole
    run — so the thread-free control loop (probes, drain advancement,
    autoscaler ticks) advances at a steady simulated monitor cadence
    while traffic flows.  Prompts are consumed round-robin in arrival
    order.

    Returns the :func:`http_poisson_load` typed-outcome contract
    (``completed + sheds + errors + failures + hangs == n_requests`` —
    zero silent drops) plus ``by_phase`` per-phase accounting.
    """
    base = base_url.rstrip("/")
    arrivals = chaos_schedule(phases, seed=seed, events=events)
    total_s = sum(d for d, _ in phases)
    n = len(arrivals)

    outcomes: List[Optional[str]] = [None] * n
    ttfts_by_idx: List[Optional[float]] = [None] * n
    tokens_out = [0] * n
    traces: List[Optional[Dict[str, Any]]] = [None] * n
    latencies: List[Optional[float]] = [None] * n
    phase_of = [idx for _, idx in arrivals]

    def fire(i: int, prompt: Sequence[int], t_submit: float) -> None:
        outcome, ttft, n_tok, trace = _fire_one(
            base, prompt, max_new_tokens, temperature, timeout_s, t_submit
        )
        tokens_out[i] = n_tok
        ttfts_by_idx[i] = ttft
        outcomes[i] = outcome
        traces[i] = trace
        latencies[i] = time.perf_counter() - t_submit

    def apply_event(ev: ChaosEvent) -> None:
        if fleet is None or ev.action == "burst":
            return
        target = ev.target
        if target is None:
            picker = getattr(fleet, "chaos_target", None)
            target = picker() if picker is not None else None
        if target is None:
            return
        try:
            if ev.action == "kill":
                fleet.kill_replica(target)
            elif ev.action == "stall":
                fleet.stall_replica(target)
            elif ev.action == "resume":
                fleet.resume_replica(target)
        except KeyError:
            pass  # victim already gone — chaos got there first

    # One merged timeline: arrivals and fault events fire in time
    # order off the same clock, with the pump ticking in between.
    timeline: List["tuple[float, int, Any]"] = [
        (at, 0, (i, prompts[i % len(prompts)])) for i, (at, _) in enumerate(arrivals)
    ]
    timeline.extend(
        (ev.at_s, 1, ev) for ev in events if ev.action != "burst"
    )
    timeline.sort(key=lambda item: (item[0], item[1]))

    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    last_pump = 0.0

    def tick_pump() -> None:
        nonlocal last_pump
        now = time.perf_counter() - t_start
        if pump is not None and now - last_pump >= pump_interval_s:
            last_pump = now
            try:
                pump()
            except Exception:  # pragma: no cover - pump must not kill load
                pass

    for at_s, _, item in timeline:
        while True:
            elapsed = time.perf_counter() - t_start
            if elapsed >= at_s:
                break
            time.sleep(min(pump_interval_s, at_s - elapsed))
            tick_pump()
        if isinstance(item, ChaosEvent):
            apply_event(item)
        else:
            i, prompt = item
            th = threading.Thread(
                target=fire,
                args=(i, prompt, time.perf_counter()),
                daemon=True,
            )
            th.start()
            threads.append(th)
        tick_pump()
    # Run out the remaining schedule (idle tail phases still need the
    # pump — that is where drain-down decisions happen), then wait for
    # stragglers, still pumping so in-flight control ops can finish.
    while time.perf_counter() - t_start < total_s:
        time.sleep(pump_interval_s)
        tick_pump()
    join_deadline = time.perf_counter() + timeout_s
    for th in threads:
        while th.is_alive() and time.perf_counter() < join_deadline:
            th.join(timeout=pump_interval_s)
            tick_pump()
    wall = time.perf_counter() - t_start

    hangs = sum(1 for th in threads if th.is_alive())
    completed = sum(1 for o in outcomes if o == "completed")
    sheds = sum(1 for o in outcomes if o == "shed")
    errors = sum(1 for o in outcomes if o and o.startswith("error:"))
    failures = sum(1 for o in outcomes if o and o.startswith("failure:"))
    total_tokens = sum(tokens_out)
    ttfts = sorted(t for t in ttfts_by_idx if t is not None)
    by_phase = []
    for idx in range(len(phases)):
        sel = [i for i in range(n) if phase_of[i] == idx]
        by_phase.append(
            {
                "n": len(sel),
                "completed": sum(
                    1 for i in sel if outcomes[i] == "completed"
                ),
                "sheds": sum(1 for i in sel if outcomes[i] == "shed"),
                "errors": sum(
                    1
                    for i in sel
                    if outcomes[i] and outcomes[i].startswith("error:")
                ),
                "failures": sum(
                    1
                    for i in sel
                    if outcomes[i] and outcomes[i].startswith("failure:")
                ),
            }
        )
    return {
        "n_requests": n,
        "completed": completed,
        "sheds": sheds,
        "errors": errors,
        "failures": failures,
        "hangs": hangs,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall > 0 else 0.0,
        "total_tokens": total_tokens,
        "ttft_mean_s": round(float(np.mean(ttfts)), 6) if ttfts else 0.0,
        "ttft_p50_s": round(_pct(ttfts, 50), 6),
        "ttft_p95_s": round(_pct(ttfts, 95), 6),
        "ttft_p99_s": round(_pct(ttfts, 99), 6),
        "by_phase": by_phase,
        "outcomes": list(outcomes),
        "trace_ids": [
            t.get("trace_id") if t is not None else None for t in traces
        ],
        "slow_requests": _slowest_traced(traces, latencies, n=5),
    }
