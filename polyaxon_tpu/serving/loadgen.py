"""Poisson-arrival load harness for the serving engine.

The existing ``serving_tokens_per_s`` bench number compares sequential
vs concurrent submission of the SAME instant — it says nothing about
tail latency under sustained load.  This harness drives the engine the
way traffic actually arrives: exponential inter-arrival gaps at a
target rate, one watcher thread per request reading its token STREAM
(so TTFT is measured at the moment the first token is readable by a
client, not when ``wait()`` returns), and aggregate tokens/s over the
loaded wall clock.

The interesting output is ``ttft_p99_s``: with full-prompt prefill a
request that arrives behind a long prompt waits the WHOLE prefill
before its own; with chunked prefill it waits at most one chunk —
bench.py runs this harness twice at the same offered load and schedule
to show exactly that difference.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(np.ceil(q / 100.0 * len(sorted_vals))) - 1)
    return sorted_vals[max(idx, 0)]


def poisson_load(
    engine: Any,
    prompts: Sequence[Sequence[int]],
    max_new_tokens: int,
    *,
    rate_rps: float,
    temperature: float = 0.0,
    seed: int = 0,
    timeout_s: float = 600.0,
) -> Dict[str, Any]:
    """Offer ``prompts`` to a RUNNING engine at ``rate_rps`` Poisson
    arrivals; returns loaded-throughput and TTFT-percentile metrics.

    The arrival schedule is drawn up front from ``seed``, so two runs
    with the same (prompts, rate, seed) offer the identical load — the
    property that makes chunked-vs-full prefill A/B comparisons fair.
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=len(prompts))

    results: List[Optional[tuple]] = [None] * len(prompts)

    def watch(i: int, req: Any, t_submit: float) -> None:
        ttft = None
        n_tokens = 0
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                tok = req.stream.get(timeout=remaining)
            except Exception:
                break
            if tok is None:
                break
            if ttft is None:
                ttft = time.perf_counter() - t_submit
            n_tokens += 1
        results[i] = (ttft, n_tokens, time.perf_counter() - t_submit, req.error)

    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    for i, prompt in enumerate(prompts):
        time.sleep(float(gaps[i]))
        t_submit = time.perf_counter()
        req = engine.submit(list(prompt), max_new_tokens, temperature)
        th = threading.Thread(
            target=watch, args=(i, req, t_submit), daemon=True
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start

    done = [r for r in results if r is not None]
    ttfts = sorted(r[0] for r in done if r[0] is not None)
    total_tokens = sum(r[1] for r in done)
    completed = sum(
        1 for r in done if r[3] is None and r[1] >= max_new_tokens
    )
    errors = sum(1 for r in done if r[3] is not None)
    return {
        "n_requests": len(prompts),
        "completed": completed,
        "errors": errors,
        "offered_rps": round(float(rate_rps), 4),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall > 0 else 0.0,
        "total_tokens": total_tokens,
        "ttft_mean_s": (
            round(float(np.mean(ttfts)), 6) if ttfts else 0.0
        ),
        "ttft_p50_s": round(_pct(ttfts, 50), 6),
        "ttft_p95_s": round(_pct(ttfts, 95), 6),
        "ttft_p99_s": round(_pct(ttfts, 99), 6),
        # Per-request TTFT by submission index (None = no first token),
        # so callers can compute percentiles over request CLASSES —
        # e.g. interactive shorts vs batch longs, which chunked prefill
        # deliberately trades against each other.
        "ttft_s": [
            (round(r[0], 6) if r is not None and r[0] is not None else None)
            for r in results
        ],
    }
