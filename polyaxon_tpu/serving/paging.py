"""Host-side bookkeeping for the paged KV cache.

Two pieces, both pure Python (the device side lives in
``models/decode.py``):

:class:`BlockAllocator` — a ref-counted free list over a fixed pool of
KV blocks.  Every in-flight sequence holds one reference per block in
its table; the shared-prefix cache holds one more per block it has
published.  A block returns to the free list only when its last holder
lets go, which is exactly the property that makes prefix SHARING safe:
retiring the request that originally computed a system prompt cannot
invalidate the neighbors still reading it.

:class:`PrefixCache` — a block-granular LRU map from token-prefix hash
chains to physical blocks.  Keys are chained per block
(``hash((prev_key, block_tokens))``), so a lookup walks the prompt one
block at a time and stops at the first miss; the stored token tuple is
compared on every hit, so a hash collision degrades to a miss instead
of serving another prompt's KV.  Eviction only considers entries whose
block has a single reference left (the cache's own) — evicting a block
a live request still reads would free nothing.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

#: Chain seed — any fixed value distinct from real chain keys' structure.
_CHAIN_SEED = "kv-prefix"

#: Physical block 0 is never handed out: the engine points inactive
#: lanes, prompt-pad writes, and unset table entries at it (see
#: models/decode.py), so its contents are garbage by design.
TRASH_BLOCK = 0


class BlockAllocator:
    """Ref-counted FIFO free list over ``num_blocks`` physical KV blocks.

    Block :data:`TRASH_BLOCK` (0) is reserved and never allocated, so a
    pool of ``num_blocks`` serves ``num_blocks - 1`` real blocks.
    ``alloc()`` returns a block with refcount 1 (or ``None`` when the
    pool is exhausted — the engine's cue to evict cached prefixes or
    park the request); ``incref``/``decref`` adjust sharing, and the
    last ``decref`` returns the block to the BACK of the free list so
    reuse order is release order.
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 usable + trash), got {num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self._free: deque = deque(range(1, self.num_blocks))
        self._refs: Dict[int, int] = {}

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        block = self._free.popleft()
        self._refs[block] = 1
        return block

    def incref(self, block: int) -> None:
        if block not in self._refs:
            raise ValueError(f"block {block} is not allocated")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; returns True when the block was freed."""
        refs = self._refs.get(block)
        if refs is None:
            raise ValueError(f"block {block} is not allocated")
        if refs == 1:
            del self._refs[block]
            self._free.append(block)
            return True
        self._refs[block] = refs - 1
        return False

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.num_blocks - 1 - len(self._free)


def truncate_table(
    table, allocator: BlockAllocator, next_pos: int, block_size: int
) -> int:
    """Speculative-decoding rollback: trim a slot's block table to the
    blocks a sequence whose next write lands at ``next_pos`` still needs.

    A verify step writes KV rows for every drafted token before knowing
    which ones the model accepts; when the accept run stops short, the
    tail rows are garbage.  Rows sharing the next-write block are simply
    overwritten in place (and masked out of attention until then), but
    blocks that lie ENTIRELY beyond ``next_pos`` hold nothing the
    sequence will read before rewriting — so this drops one reference on
    each (``table`` entries after the block containing ``next_pos``,
    reset to -1) and returns how many references were dropped.

    Uses ``decref``, never a force-free: a dropped block returns to the
    free list only when no other holder remains, so prefix-cache shares
    and COW invariants survive rollback by construction.  (In practice
    the trimmed blocks are always private — they were faulted for this
    lane's own draft span, past the prompt blocks sharing could cover.)

    ``table`` is the engine's host-side row (a mutable int array,
    -1 = unset), mutated in place.
    """
    keep = int(next_pos) // int(block_size)
    freed = 0
    for bi in range(keep + 1, len(table)):
        block = int(table[bi])
        if block < 0:
            break  # tables fill contiguously; nothing set past here
        allocator.decref(block)
        table[bi] = -1
        freed += 1
    return freed


class PrefixCache:
    """Block-granular shared-prefix cache over a :class:`BlockAllocator`.

    ``match()`` walks a prompt's full blocks against the chain map and
    returns the longest run of cached blocks, taking one reference per
    returned block on the caller's behalf.  ``offer()`` publishes a
    finished prompt's blocks (taking the cache's own reference on each
    newly published block).  ``evict()`` reclaims LRU entries whose
    block nobody else holds.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self._alloc = allocator
        self.block_size = int(block_size)
        # chain key -> (physical block, the block's token tuple)
        self._entries: "OrderedDict[int, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self.hits = 0
        self.lookups = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def hit_rate(self) -> float:
        """Block-granular hit rate over the cache's lifetime."""
        return self.hits / self.lookups if self.lookups else 0.0

    def _keys_for(self, prompt: Sequence[int]) -> List[Tuple[int, Tuple[int, ...]]]:
        """Chained (key, tokens) per FULL block of the prompt."""
        out = []
        key: object = _CHAIN_SEED
        for i in range(len(prompt) // self.block_size):
            toks = tuple(prompt[i * self.block_size : (i + 1) * self.block_size])
            key = hash((key, toks))
            out.append((key, toks))
        return out

    def match(self, prompt: Sequence[int]) -> List[int]:
        """Longest cached block-prefix of ``prompt``; increfs each
        returned block (the caller owns those references)."""
        blocks: List[int] = []
        for key, toks in self._keys_for(prompt):
            self.lookups += 1
            entry = self._entries.get(key)
            if entry is None or entry[1] != toks:
                break
            self.hits += 1
            self._entries.move_to_end(key)
            self._alloc.incref(entry[0])
            blocks.append(entry[0])
        return blocks

    def offer(self, prompt: Sequence[int], blocks: Sequence[int]) -> None:
        """Publish a prompt's full blocks.  ``blocks[i]`` must hold block
        ``i``'s KV; already published prefixes keep their existing block
        (first writer wins — later identical blocks stay private)."""
        for (key, toks), block in zip(self._keys_for(prompt), blocks):
            entry = self._entries.get(key)
            if entry is None:
                self._alloc.incref(block)
                self._entries[key] = (block, toks)
            self._entries.move_to_end(key)

    def evict(self, need: int = 1) -> int:
        """Drop up to ``need`` LRU entries whose block only the cache
        still references (freeing them); returns how many blocks freed."""
        freed = 0
        for key in list(self._entries):
            if freed >= need:
                break
            block, _ = self._entries[key]
            if self._alloc.refcount(block) == 1:
                del self._entries[key]
                self._alloc.decref(block)
                freed += 1
        return freed

    def drop_all(self) -> int:
        """Evict everything evictable (shutdown / tests)."""
        return self.evict(need=len(self._entries))
