"""Host-side bookkeeping for the paged KV cache.

Three pieces, all pure Python (the device side lives in
``models/decode.py``):

:class:`BlockAllocator` — a ref-counted free list over a fixed pool of
KV blocks.  Every in-flight sequence holds one reference per block in
its table; the shared-prefix cache holds one more per block it has
published.  A block returns to the free list only when its last holder
lets go, which is exactly the property that makes prefix SHARING safe:
retiring the request that originally computed a system prompt cannot
invalidate the neighbors still reading it.

:class:`PrefixCache` — a block-granular LRU map from token-prefix hash
chains to physical blocks.  Keys are chained per block
(``hash((prev_key, block_tokens))``), so a lookup walks the prompt one
block at a time and stops at the first miss; the stored token tuple is
compared on every hit, so a hash collision degrades to a miss instead
of serving another prompt's KV.  Eviction only considers entries whose
block has a single reference left (the cache's own) — evicting a block
a live request still reads would free nothing.

:class:`HostKVTier` — the host-memory tier under the device pool.  It
stores exported block payloads (numpy leaf trees mirroring the pool
layout bit-exact) for two populations: a parked sequence's spilled
private blocks (pinned — correctness state) and demoted prefix-cache
blocks (a bounded LRU — pure cache).  The device copies themselves live
in the engine; this class is pure bookkeeping.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: Chain seed — any fixed value distinct from real chain keys' structure.
_CHAIN_SEED = "kv-prefix"

#: Physical block 0 is never handed out: the engine points inactive
#: lanes, prompt-pad writes, and unset table entries at it (see
#: models/decode.py), so its contents are garbage by design.
TRASH_BLOCK = 0


class BlockAllocator:
    """Ref-counted FIFO free list over ``num_blocks`` physical KV blocks.

    Block :data:`TRASH_BLOCK` (0) is reserved and never allocated, so a
    pool of ``num_blocks`` serves ``num_blocks - 1`` real blocks.
    ``alloc()`` returns a block with refcount 1 (or ``None`` when the
    pool is exhausted — the engine's cue to evict cached prefixes or
    park the request); ``incref``/``decref`` adjust sharing, and the
    last ``decref`` returns the block to the BACK of the free list so
    reuse order is release order.
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 usable + trash), got {num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self._free: deque = deque(range(1, self.num_blocks))
        self._refs: Dict[int, int] = {}

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        block = self._free.popleft()
        self._refs[block] = 1
        return block

    def incref(self, block: int) -> None:
        if block not in self._refs:
            raise ValueError(f"block {block} is not allocated")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; returns True when the block was freed."""
        refs = self._refs.get(block)
        if refs is None:
            raise ValueError(f"block {block} is not allocated")
        if refs == 1:
            del self._refs[block]
            self._free.append(block)
            return True
        self._refs[block] = refs - 1
        return False

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.num_blocks - 1 - len(self._free)


def truncate_table(
    table, allocator: BlockAllocator, next_pos: int, block_size: int
) -> int:
    """Speculative-decoding rollback: trim a slot's block table to the
    blocks a sequence whose next write lands at ``next_pos`` still needs.

    A verify step writes KV rows for every drafted token before knowing
    which ones the model accepts; when the accept run stops short, the
    tail rows are garbage.  Rows sharing the next-write block are simply
    overwritten in place (and masked out of attention until then), but
    blocks that lie ENTIRELY beyond ``next_pos`` hold nothing the
    sequence will read before rewriting — so this drops one reference on
    each (``table`` entries after the block containing ``next_pos``,
    reset to -1) and returns how many references were dropped.

    Uses ``decref``, never a force-free: a dropped block returns to the
    free list only when no other holder remains, so prefix-cache shares
    and COW invariants survive rollback by construction.  (In practice
    the trimmed blocks are always private — they were faulted for this
    lane's own draft span, past the prompt blocks sharing could cover.)

    ``table`` is the engine's host-side row (a mutable int array,
    -1 = unset), mutated in place.
    """
    keep = int(next_pos) // int(block_size)
    freed = 0
    for bi in range(keep + 1, len(table)):
        block = int(table[bi])
        if block < 0:
            break  # tables fill contiguously; nothing set past here
        allocator.decref(block)
        table[bi] = -1
        freed += 1
    return freed


#: Entry-block sentinel for a prefix-cache entry whose payload lives in
#: the host tier (no device block); ``PrefixCache._demoted`` maps the
#: entry's key to its tier handle.
DEMOTED = -1


class HostKVTier:
    """Host-memory KV block store — the offload tier under the device pool.

    Entries are opaque payloads (dicts of numpy arrays, one per pool
    leaf, so an int8 pool spills int8 rows + scales bit-exact) keyed by
    a monotonically increasing handle.  Two populations share the tier:

    - **pinned** — a parked sequence's spilled private blocks.  This is
      correctness state (the KV exists nowhere else), so pinned entries
      are never dropped and don't count against ``capacity_blocks``.
    - **unpinned** — demoted prefix-cache blocks.  Pure cache: bounded
      by ``capacity_blocks`` (0 = unbounded) with LRU drop; each drop
      invokes ``on_drop(handle)`` so the owning cache forgets the entry.
    """

    def __init__(self, capacity_blocks: int = 0) -> None:
        if capacity_blocks < 0:
            raise ValueError(
                f"capacity_blocks must be >= 0, got {capacity_blocks}"
            )
        self.capacity_blocks = int(capacity_blocks)
        self._data: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._pinned: set = set()
        self._next_handle = 1
        self.on_drop: Optional[Callable[[int], None]] = None
        self.spilled_total = 0
        self.restored_total = 0
        self.dropped_total = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, handle: int) -> bool:
        return handle in self._data

    @property
    def n_pinned(self) -> int:
        return len(self._pinned)

    @property
    def n_unpinned(self) -> int:
        return len(self._data) - len(self._pinned)

    @property
    def nbytes(self) -> int:
        """Host bytes currently held (all payload leaves)."""
        return sum(
            arr.nbytes
            for tree in self._data.values()
            for arr in tree.values()
        )

    def put(self, data: Dict[str, Any], pinned: bool = False) -> Optional[int]:
        """Admit one payload; returns its handle, or ``None`` when the
        unpinned budget is exhausted and nothing can be dropped (pinned
        admissions never fail — losing parked state would lose KV)."""
        if not pinned and self.capacity_blocks:
            while self.n_unpinned >= self.capacity_blocks:
                victim = next(
                    (h for h in self._data if h not in self._pinned), None
                )
                if victim is None:
                    return None
                self._drop(victim)
        handle = self._next_handle
        self._next_handle += 1
        self._data[handle] = data
        if pinned:
            self._pinned.add(handle)
        self.spilled_total += 1
        return handle

    def get(self, handle: int) -> Dict[str, Any]:
        """Read a payload without removing it (refreshes LRU position)."""
        data = self._data[handle]
        self._data.move_to_end(handle)
        return data

    def pop(self, handle: int) -> Dict[str, Any]:
        """Remove and return a payload (the restore path)."""
        self._pinned.discard(handle)
        self.restored_total += 1
        return self._data.pop(handle)

    def discard(self, handle: int) -> None:
        """Drop a payload without restoring it (retire/fail paths);
        unknown handles are ignored."""
        self._pinned.discard(handle)
        self._data.pop(handle, None)

    def _drop(self, handle: int) -> None:
        self._data.pop(handle)
        self.dropped_total += 1
        if self.on_drop is not None:
            self.on_drop(handle)


class PrefixCache:
    """Block-granular shared-prefix cache over a :class:`BlockAllocator`.

    ``match()`` walks a prompt's full blocks against the chain map and
    returns the longest run of cached blocks, taking one reference per
    returned block on the caller's behalf.  ``offer()`` publishes a
    finished prompt's blocks (taking the cache's own reference on each
    newly published block).  ``evict()`` reclaims LRU entries whose
    block nobody else holds.

    With a host tier attached (:meth:`attach_tier`), eviction DEMOTES
    instead: the cold entry's payload moves to host memory, its device
    block frees, and the entry stays matchable — a later hit restores it
    through a fresh device block (verify-on-hit unchanged, since the
    stored token tuple never leaves the entry).  Entries also remember
    their FULL prefix token chain, which is what makes them persistable:
    chain keys are built with Python's process-randomized string hash,
    so a store must carry tokens, not keys, and rebuild keys on load.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self._alloc = allocator
        self.block_size = int(block_size)
        # chain key -> (physical block | DEMOTED, the block's token tuple)
        self._entries: "OrderedDict[int, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        # chain key -> the FULL prefix token chain ending at this block
        # (ancestors included) — the persistable identity of an entry.
        self._chains: Dict[int, Tuple[int, ...]] = {}
        # Demoted entries: chain key <-> host tier handle.
        self._demoted: Dict[int, int] = {}
        self._handle_key: Dict[int, int] = {}
        self._tier: Optional[HostKVTier] = None
        self._spill: Optional[Callable[[int], Optional[int]]] = None
        self._restore: Optional[Callable[[int, int], None]] = None
        self._alloc_fn: Optional[Callable[[], Optional[int]]] = None
        self.hits = 0
        self.lookups = 0
        self.evictions = 0
        self.demotions = 0
        self.demote_restores = 0
        #: Monotonic content-change counter: bumped whenever the entry
        #: SET changes (offer/install adds, evict/demote/restore/drop
        #: removals or tier moves).  len() can't detect churn at
        #: constant size, so persistence freshness keys off this.
        self.mutations = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def hit_rate(self) -> float:
        """Block-granular hit rate over the cache's lifetime."""
        return self.hits / self.lookups if self.lookups else 0.0

    @property
    def misses(self) -> int:
        return self.lookups - self.hits

    @property
    def n_demoted(self) -> int:
        """Entries currently resident in the host tier (no device block)."""
        return len(self._demoted)

    def attach_tier(
        self,
        tier: HostKVTier,
        spill: Callable[[int], Optional[int]],
        restore: Callable[[int, int], None],
        alloc: Callable[[], Optional[int]],
    ) -> None:
        """Arm demotion over ``tier``.  ``spill(block)`` copies a device
        block's payload into the tier (returns its handle, or ``None``
        when the tier refuses — then the entry hard-evicts as before);
        ``restore(handle, block)`` writes a payload back into a fresh
        device block and removes it from the tier; ``alloc()`` provides
        that fresh block (the engine passes its evict-then-retry
        allocator, so restoring a hot prefix may demote a colder one).
        The tier's ``on_drop`` is wired back here so a capacity drop
        forgets the corresponding entry."""
        self._tier = tier
        self._spill = spill
        self._restore = restore
        self._alloc_fn = alloc
        tier.on_drop = self._forget_handle

    def _forget_handle(self, handle: int) -> None:
        """Host-tier capacity drop: the demoted entry's payload is gone,
        so the entry itself must go too (a match against it would
        otherwise restore garbage)."""
        key = self._handle_key.pop(handle, None)
        if key is None:
            return
        self._demoted.pop(key, None)
        self._entries.pop(key, None)
        self._chains.pop(key, None)
        self.evictions += 1
        self.mutations += 1

    def _keys_for(self, prompt: Sequence[int]) -> List[Tuple[int, Tuple[int, ...]]]:
        """Chained (key, tokens) per FULL block of the prompt."""
        out = []
        key: object = _CHAIN_SEED
        for i in range(len(prompt) // self.block_size):
            toks = tuple(prompt[i * self.block_size : (i + 1) * self.block_size])
            key = hash((key, toks))
            out.append((key, toks))
        return out

    def match(self, prompt: Sequence[int]) -> List[int]:
        """Longest cached block-prefix of ``prompt``; increfs each
        returned block (the caller owns those references).  A demoted
        entry on the walk restores through a fresh device block first
        (host→device copy); if the pool can't provide one even after
        demoting colder entries, the walk stops there — a miss, never an
        error."""
        blocks: List[int] = []
        for key, toks in self._keys_for(prompt):
            self.lookups += 1
            entry = self._entries.get(key)
            if entry is None or entry[1] != toks:
                break
            block = entry[0]
            if block < 0:
                block = self._restore_entry(key, toks)
                if block is None:
                    break
            self.hits += 1
            self._entries.move_to_end(key)
            self._alloc.incref(block)
            blocks.append(block)
        return blocks

    def _restore_entry(self, key: int, toks: Tuple[int, ...]) -> Optional[int]:
        """Bring one demoted entry back on-device; returns its fresh
        block or ``None`` (allocation failed — entry stays demoted)."""
        handle = self._demoted.get(key)
        if handle is None or self._restore is None:
            return None
        # MRU first on BOTH levels: the allocation below may demote LRU
        # entries to make room (cache side), and each demotion's
        # tier.put may LRU-drop tier payloads (tier side) — neither
        # cascade may land on the entry being restored.
        self._entries.move_to_end(key)
        if self._tier is not None and handle in self._tier:
            self._tier.get(handle)
        alloc = self._alloc_fn or self._alloc.alloc
        block = alloc()
        if block is None:
            return None
        # A tier smaller than the eviction cascade can still have
        # dropped this handle during alloc (on_drop already forgot the
        # entry): the payload is gone, so treat it as a miss.
        if self._demoted.get(key) != handle or (
            self._tier is not None and handle not in self._tier
        ):
            self._alloc.decref(block)
            return None
        self._restore(handle, block)
        del self._demoted[key]
        self._handle_key.pop(handle, None)
        self._entries[key] = (block, toks)
        self.demote_restores += 1
        self.mutations += 1
        return block

    def offer(self, prompt: Sequence[int], blocks: Sequence[int]) -> None:
        """Publish a prompt's full blocks.  ``blocks[i]`` must hold block
        ``i``'s KV; already published prefixes keep their existing block
        (first writer wins — later identical blocks stay private)."""
        chain: List[int] = []
        for (key, toks), block in zip(self._keys_for(prompt), blocks):
            chain.extend(toks)
            entry = self._entries.get(key)
            if entry is None:
                self._alloc.incref(block)
                self._entries[key] = (block, toks)
                self._chains[key] = tuple(chain)
                self.mutations += 1
            self._entries.move_to_end(key)

    def install(self, chain_tokens: Sequence[int], block: int) -> bool:
        """Register a persisted prefix block (warm boot): ``chain_tokens``
        is the FULL token prefix ending at this block, and the caller —
        who has already written the block's KV — transfers its fresh
        refcount-1 allocation to the cache.  First writer wins like
        ``offer``: a pre-existing entry keeps its block and the caller's
        is freed.  Returns True when the entry was installed."""
        keys = self._keys_for(chain_tokens)
        if not keys:
            self._alloc.decref(block)
            return False
        key, toks = keys[-1]
        if key in self._entries:
            self._alloc.decref(block)
            return False
        self._entries[key] = (block, toks)
        self._chains[key] = tuple(int(t) for t in chain_tokens)
        self._entries.move_to_end(key)
        self.mutations += 1
        return True

    def hottest_chains(
        self, limit: int
    ) -> List[Tuple[Tuple[int, ...], int, Optional[int]]]:
        """Up to ``limit`` entries worth persisting, hottest-first WITH
        chain closure: an entry only helps a future ``match`` walk if its
        ancestors are stored too, so each hot entry pulls in its whole
        chain root-first.  (Taking the raw MRU tail would do the
        opposite — ``match`` moves ancestors to the end *before* their
        descendants, so a tail cut keeps children and orphans parents.)
        Returns ``(full_chain_tokens, block_or_DEMOTED, handle_or_None)``
        tuples, ancestors before descendants."""
        out: List[Tuple[Tuple[int, ...], int, Optional[int]]] = []
        seen: set = set()
        for key in reversed(self._entries):
            if len(out) >= limit:
                break
            chain = self._chains.get(key)
            if chain is None:
                continue
            for k2, _ in self._keys_for(chain):
                if k2 in seen or len(out) >= limit:
                    continue
                entry = self._entries.get(k2)
                chain2 = self._chains.get(k2)
                if entry is None or chain2 is None:
                    continue
                seen.add(k2)
                out.append((chain2, entry[0], self._demoted.get(k2)))
        return out

    def evict(self, need: int = 1, demote: Optional[bool] = None) -> int:
        """Reclaim up to ``need`` device blocks from LRU entries whose
        block only the cache still references; returns how many device
        blocks freed.  With a host tier attached (and ``demote`` not
        forced off) the entry's payload moves to the tier instead of
        vanishing — the device block frees either way, but a demoted
        entry stays matchable.  A tier refusal (unpinned capacity
        exhausted) falls back to the hard evict."""
        if demote is None:
            demote = self._tier is not None
        freed = 0
        for key in list(self._entries):
            if freed >= need:
                break
            # The demote branch's spill can re-enter _forget_handle (a
            # tier capacity drop fires on_drop) and delete OTHER demoted
            # entries mid-iteration, so keys from the snapshot above may
            # be gone by the time the walk reaches them.
            entry = self._entries.get(key)
            if entry is None:
                continue
            block, toks = entry
            if block < 0:
                continue  # already demoted: holds no device block
            if self._alloc.refcount(block) != 1:
                continue
            if demote and self._spill is not None:
                handle = self._spill(block)
                if handle is not None:
                    self._demoted[key] = handle
                    self._handle_key[handle] = key
                    self._entries[key] = (DEMOTED, toks)
                    self._alloc.decref(block)
                    self.demotions += 1
                    self.mutations += 1
                    freed += 1
                    continue
            self._entries.pop(key, None)
            self._chains.pop(key, None)
            self._alloc.decref(block)
            self.evictions += 1
            self.mutations += 1
            freed += 1
        return freed

    def drop_all(self) -> int:
        """Evict everything evictable (shutdown / tests) — hard evicts,
        never demotes, and forgets demoted entries' host payloads too."""
        freed = self.evict(need=len(self._entries), demote=False)
        for key in [k for k, e in self._entries.items() if e[0] < 0]:
            handle = self._demoted.pop(key)
            self._handle_key.pop(handle, None)
            if self._tier is not None:
                self._tier.discard(handle)
            del self._entries[key]
            self._chains.pop(key, None)
            self.evictions += 1
            self.mutations += 1
        return freed
