"""Persistent prefix-KV store: warm replica boot for the serving engine.

Serializes hot :class:`~polyaxon_tpu.serving.paging.PrefixCache` blocks
(payload + the FULL token chain that identifies each entry) under a
store directory — normally ``StoreLayout.kv_cache_dir`` — so a
replacement or scale-up replica can hydrate its prefix cache during
warmup and serve its first requests prefix-warm instead of paying cold
TTFT exactly when the fleet is most loaded.

Durability protocol is the checkpoint one (``runtime/checkpoint.py``):
versioned snapshot directories plus a ``.complete/<version>`` marker
written LAST, each rename atomic.  A crash mid-write leaves either the
previous complete version or an ignorable torn directory — readers
trust only marked versions.  Concurrent writers (several replicas
persisting into one shared dir) race benignly: version numbers are
claimed by the directory rename, a loser just retries one higher.

Two deliberate format choices:

- entries store **tokens, not chain keys** — ``PrefixCache`` keys are
  built with Python's string ``hash()``, which is randomized per
  process; the loader rebuilds keys in its own process via the cache's
  own chain walk.
- payloads store the **pool's storage leaves verbatim** — an int8 pool
  persists int8 rows + f32 scales, so quantization halves the bytes on
  disk exactly as it does in HBM, and a loaded block is the original's
  bits (never a requantization).  Leaf dtypes are recorded BY NAME next
  to the payload: ``np.load`` reads extension dtypes (bfloat16 — the
  TPU default) back as raw void bytes, so the loader view-casts each
  leaf to its recorded dtype instead of handing jit an invalid array.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

#: Marker directory: ``<root>/.complete/<version>`` exists iff snapshot
#: ``<root>/<version>/`` finished writing (same protocol as
#: ``runtime/checkpoint.py``).
_COMPLETE_DIR = ".complete"

#: Complete snapshots kept after a successful save (older versions GC).
_KEEP_VERSIONS = 2

#: One persisted prefix block: (full chain tokens, {pool leaf: array}).
Entry = Tuple[Tuple[int, ...], Dict[str, np.ndarray]]


def complete_versions(root: Union[str, Path]) -> List[int]:
    """All snapshot versions whose finalize marker exists, ascending."""
    root = Path(root)
    marker_dir = root / _COMPLETE_DIR
    if not marker_dir.is_dir():
        return []
    return sorted(
        int(p.name)
        for p in marker_dir.iterdir()
        if p.name.isdigit() and (root / p.name).is_dir()
    )


def latest_complete_version(root: Union[str, Path]) -> Optional[int]:
    versions = complete_versions(root)
    return versions[-1] if versions else None


def save_prefix_store(
    root: Union[str, Path],
    entries: Sequence[Entry],
    meta: Dict[str, Any],
) -> Optional[int]:
    """Write one snapshot (payloads + chains + ``meta``); returns its
    version, or ``None`` when nothing was written (no entries, or the
    version race lost too many times).  ``meta`` is the compatibility
    fingerprint the loader matches exactly — geometry, kv dtype, and the
    caller's model signature."""
    if not entries:
        return None
    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    (root / _COMPLETE_DIR).mkdir(exist_ok=True)
    for attempt in range(3):
        version = (latest_complete_version(root) or 0) + 1 + attempt
        final = root / str(version)
        if final.exists():
            continue  # a concurrent writer claimed it (possibly torn)
        tmp = root / f"{version}.tmp-{os.getpid()}"
        try:
            tmp.mkdir()
            arrays: Dict[str, np.ndarray] = {}
            records = []
            for i, (chain, data) in enumerate(entries):
                records.append(
                    {
                        "tokens": [int(t) for t in chain],
                        "leaves": sorted(data),
                        "dtypes": {
                            name: str(np.asarray(arr).dtype)
                            for name, arr in data.items()
                        },
                    }
                )
                for name, arr in data.items():
                    arrays[f"e{i}.{name}"] = np.asarray(arr)
            np.savez(tmp / "blocks.npz", **arrays)
            (tmp / "meta.json").write_text(
                json.dumps({"meta": dict(meta), "entries": records})
            )
            os.replace(tmp, final)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            continue
        # Data is in place — now, and only now, the finalize marker.
        marker = root / _COMPLETE_DIR / str(version)
        marker_tmp = root / _COMPLETE_DIR / f"{version}.tmp-{os.getpid()}"
        marker_tmp.write_text("")
        os.replace(marker_tmp, marker)
        _gc_versions(root)
        return version
    return None


def load_prefix_store(
    root: Union[str, Path],
    expect: Optional[Dict[str, Any]] = None,
) -> Optional[List[Entry]]:
    """Entries of the newest COMPLETE snapshot, ancestors-first — or
    ``None`` when there is no usable store (missing, torn, unreadable,
    or any ``expect`` key differs from the stored meta: a geometry or
    model-signature mismatch makes the payloads garbage, so the loader
    walks away rather than serving wrong KV)."""
    root = Path(root)
    version = latest_complete_version(root)
    if version is None:
        return None
    snap = root / str(version)
    try:
        doc = json.loads((snap / "meta.json").read_text())
        stored = doc["meta"]
        if expect:
            for key, want in expect.items():
                if stored.get(key) != want:
                    return None
        out: List[Entry] = []
        with np.load(snap / "blocks.npz") as z:
            for i, rec in enumerate(doc["entries"]):
                dtypes = rec.get("dtypes") or {}
                data = {}
                for name in rec["leaves"]:
                    arr = z[f"e{i}.{name}"]
                    want = dtypes.get(name)
                    if want and str(arr.dtype) != want:
                        arr = arr.view(_np_dtype(want))
                    data[name] = arr
                out.append((tuple(int(t) for t in rec["tokens"]), data))
        return out
    except Exception:
        return None


def _np_dtype(name: str) -> np.dtype:
    """Resolve a recorded dtype name; extension names (``bfloat16``)
    only resolve once ``ml_dtypes`` has registered them with numpy."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers bfloat16 et al.)

        return np.dtype(name)


def _gc_versions(root: Path) -> None:
    """Keep the newest ``_KEEP_VERSIONS`` complete snapshots; older
    versions lose their marker FIRST (so a reader never trusts a
    half-deleted dir), then their data.  Stray tmp dirs are left alone —
    they may belong to a live concurrent writer."""
    for version in complete_versions(root)[:-_KEEP_VERSIONS]:
        marker = root / _COMPLETE_DIR / str(version)
        try:
            marker.unlink()
        except OSError:
            continue
        shutil.rmtree(root / str(version), ignore_errors=True)
