"""Fleet request router: health-aware balancing over lm_server replicas.

The control-plane half of multi-replica serving.  ``lm_server``
replicas (PR 7's ``/healthz`` readiness gate + PR 6's ``/v1/stats``
occupancy) stay single-engine simple; everything fleet-shaped lives
here:

- **readiness + occupancy balancing** — a background probe thread polls
  every replica's ``/healthz`` and ``/v1/stats``; routing considers only
  ``ready`` replicas and picks the least-loaded by slot occupancy +
  queue depth (+ the router's own in-flight count, so a burst between
  probes doesn't pile onto one replica);
- **prefix affinity** — the first ``affinity_tokens`` prompt ids are
  hashed rendezvous-style over the ready set, so shared-prefix traffic
  lands on the replica whose ``PrefixCache`` already holds the blocks;
  when the affine replica is busier than the least-loaded alternative,
  its probed ``prefix_hit_rate`` decides how much load excess affinity
  is worth (``affinity_slack`` + hit_rate × ``affinity_hit_slack``) —
  a genuinely warm cache justifies a busier replica, a cold one does
  not; falls back to least-loaded beyond that slack or when the affine
  replica is saturated, draining, or ejected;
- **load shedding** — when the fleet-mean occupancy crosses
  ``shed_occupancy`` the router refuses admission with a typed 429
  (``error.kind == "overloaded"``) and a ``Retry-After`` header, same
  shape as the engine's own deadlock-shed;
- **bounded failover** — a connection error or replica death before the
  response is read is retried on a different replica up to
  ``retry_limit`` times.  This is safe because ``/generate`` admission
  is idempotent until the first token reaches the CLIENT (the response
  is unread, so re-running it elsewhere duplicates at most wasted
  decode, never client-visible output).  Exhausted retries return ONE
  typed error — never a hang;
- **ejection with exponential backoff** — ``eject_failures``
  consecutive probe/request failures eject a replica; re-admission is
  re-probed after a backoff that doubles per consecutive failed
  re-admission (capped), and a successful probe re-admits and resets it;
- **drain lifecycle** — ``drain(name)`` stops routing to a replica,
  lets in-flight requests finish (watched via probes + the router's own
  in-flight count), and marks it ``drained`` at completion or at a
  deadline; the fleet layer (``serving/fleet.py``) turns that into
  stop-old/launch-replacement.

Every state transition lands on the stats backend
(``fleet_replica_state{replica}`` gauge; ``router_sheds_total`` /
``router_retries_total`` / ``router_ejections_total`` counters) so
``/metrics`` and the ``check_fleet`` probe see the same truth.
"""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.client import HTTPException
from typing import Any, Callable, Dict, List, Optional, Sequence

from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_int
from polyaxon_tpu.stats import MemoryStats
from polyaxon_tpu.stats.metrics import labeled_key
from polyaxon_tpu.tracking.trace import (
    TraceContext,
    chrome_trace,
    extract,
    get_tracer,
    inject,
    new_trace_id,
)

__all__ = ["FleetRouter", "Replica", "RouterError", "make_router_handler"]

#: Replica lifecycle states (the ``fleet_replica_state`` gauge encodes
#: them in this order).
STATES = ("warming", "ready", "draining", "ejected", "drained", "dead")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}


class RouterError(RuntimeError):
    """A typed routing refusal/failure: HTTP status + machine-readable
    ``kind`` (+ optional ``Retry-After`` seconds for shed responses)."""

    def __init__(
        self,
        kind: str,
        message: str,
        *,
        status: int = 503,
        retry_after_s: Optional[float] = None,
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.status = int(status)
        self.retry_after_s = retry_after_s

    def payload(self) -> Dict[str, Any]:
        return {"error": {"kind": self.kind, "message": str(self)}}


class Replica:
    """One tracked backend: probe-derived health + router-side load."""

    def __init__(self, name: str, base_url: str) -> None:
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.state = "warming"
        #: Probe-derived engine load (slots_active, queue_depth, slots).
        self.slots = 1
        self.slots_active = 0
        self.queue_depth = 0
        self.prefix_hit_rate = 0.0
        #: Requests this router currently has in flight against it —
        #: fresher than the last probe, so bursts spread correctly.
        self.inflight = 0
        self.requests = 0
        self.consecutive_failures = 0
        #: Consecutive failed re-admission probes since ejection — the
        #: exponent of the re-admission backoff.
        self.eject_streak = 0
        self.ejected_until = 0.0
        self.drain_deadline: Optional[float] = None
        self.drain_started: Optional[float] = None
        self.last_probe_at = 0.0
        self.last_error: Optional[str] = None
        #: Full ``/v1/stats`` body from the last successful probe — the
        #: scrape phase reads history off it instead of re-connecting.
        self.last_stats: Dict[str, Any] = {}

    def load(self) -> float:
        """Occupancy estimate in [0, inf): probed engine load plus the
        router's own unprobed in-flight delta, per slot."""
        engine_busy = self.slots_active + self.queue_depth
        # inflight requests already visible in the probe are counted
        # once: take the max, not the sum, of the two views.
        busy = max(engine_busy, self.inflight)
        return busy / max(1, self.slots)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "base_url": self.base_url,
            "state": self.state,
            "slots": self.slots,
            "slots_active": self.slots_active,
            "queue_depth": self.queue_depth,
            "load": round(self.load(), 4),
            "inflight": self.inflight,
            "requests": self.requests,
            "consecutive_failures": self.consecutive_failures,
            "eject_streak": self.eject_streak,
            "ejected_until": self.ejected_until,
            "prefix_cache_hit_rate": self.prefix_hit_rate,
            "last_error": self.last_error,
        }


def _http_json(
    url: str,
    payload: Optional[Dict[str, Any]] = None,
    *,
    timeout: float,
    headers: Optional[Dict[str, str]] = None,
) -> "tuple[int, Dict[str, Any]]":
    """One JSON round-trip; HTTP error statuses return (code, body),
    connection-level failures raise OSError/HTTPException."""
    data = None
    all_headers = dict(headers or {})
    if payload is not None:
        data = json.dumps(payload).encode()
        all_headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=all_headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, body


class FleetRouter:
    """Routes ``/generate`` traffic across N ``lm_server`` replicas.

    All thresholds default from the ``POLYAXON_TPU_ROUTER_*`` knob
    catalog; constructor arguments override them (tests shrink the
    timescales, production reads the env).
    """

    def __init__(
        self,
        *,
        stats: Any = None,
        probe_interval_s: Optional[float] = None,
        probe_timeout_s: Optional[float] = None,
        request_timeout_s: Optional[float] = None,
        shed_occupancy: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        retry_limit: Optional[int] = None,
        eject_failures: Optional[int] = None,
        eject_backoff_s: Optional[float] = None,
        eject_backoff_max_s: Optional[float] = None,
        affinity_tokens: Optional[int] = None,
        affinity_slack: Optional[float] = None,
        affinity_hit_slack: Optional[float] = None,
        on_drained: Optional[Callable[[str, bool], None]] = None,
    ) -> None:
        self.metrics = stats if stats is not None else MemoryStats()
        self.probe_interval_s = (
            probe_interval_s
            if probe_interval_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_PROBE_INTERVAL_S")
        )
        self.probe_timeout_s = (
            probe_timeout_s
            if probe_timeout_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_PROBE_TIMEOUT_S")
        )
        self.request_timeout_s = (
            request_timeout_s
            if request_timeout_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_REQUEST_TIMEOUT_S")
        )
        self.shed_occupancy = (
            shed_occupancy
            if shed_occupancy is not None
            else knob_float("POLYAXON_TPU_ROUTER_SHED_OCCUPANCY")
        )
        self.retry_after_s = (
            retry_after_s
            if retry_after_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_RETRY_AFTER_S")
        )
        self.retry_limit = (
            retry_limit
            if retry_limit is not None
            else knob_int("POLYAXON_TPU_ROUTER_RETRY_LIMIT")
        )
        self.eject_failures = (
            eject_failures
            if eject_failures is not None
            else knob_int("POLYAXON_TPU_ROUTER_EJECT_FAILURES")
        )
        self.eject_backoff_s = (
            eject_backoff_s
            if eject_backoff_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_EJECT_BACKOFF_S")
        )
        self.eject_backoff_max_s = (
            eject_backoff_max_s
            if eject_backoff_max_s is not None
            else knob_float("POLYAXON_TPU_ROUTER_EJECT_BACKOFF_MAX_S")
        )
        self.affinity_tokens = (
            affinity_tokens
            if affinity_tokens is not None
            else knob_int("POLYAXON_TPU_ROUTER_AFFINITY_TOKENS")
        )
        self.affinity_slack = (
            affinity_slack
            if affinity_slack is not None
            else knob_float("POLYAXON_TPU_ROUTER_AFFINITY_SLACK")
        )
        self.affinity_hit_slack = (
            affinity_hit_slack
            if affinity_hit_slack is not None
            else knob_float("POLYAXON_TPU_ROUTER_AFFINITY_HIT_SLACK")
        )
        self.on_drained = on_drained
        #: Request tracing: when on, every proxied /generate gets a root
        #: span + per-attempt child spans, and the traceparent rides the
        #: upstream hop so replica/engine spans join the same trace.
        self.trace_requests = knob_bool("POLYAXON_TPU_TRACE_REQUESTS")
        self._replicas: Dict[str, Replica] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Lifetime counters, mirrored onto the stats backend for /metrics.
        self.counters = {
            "requests": 0,
            "sheds": 0,
            "retries": 0,
            "failovers": 0,
            "ejections": 0,
            "readmissions": 0,
            "drains": 0,
            "upstream_errors": 0,
        }

    # -- membership -----------------------------------------------------------
    def add_replica(self, name: str, base_url: str) -> Replica:
        with self._lock:
            rep = Replica(name, base_url)
            self._replicas[name] = rep
        self._set_state(rep, "warming")
        return rep

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)

    def replica(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(name)

    def replica_names(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._probe_loop, name="fleet-router-probe", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- probing / health -----------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_all()
            except Exception:  # pragma: no cover - probe must never die
                pass

    def probe_all(self, now: Optional[float] = None) -> None:
        """One probe pass over every replica (also callable synchronously
        from tests — the loop thread is just a driver)."""
        now = now if now is not None else time.time()
        for name in self.replica_names():
            rep = self.replica(name)
            if rep is None:
                continue
            if rep.state == "ejected" and now < rep.ejected_until:
                continue  # still backing off
            if rep.state == "drained":
                continue
            self.probe_one(rep, now)
        self._advance_drains(now)

    def probe_one(self, rep: Replica, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        rep.last_probe_at = now
        try:
            code, health = _http_json(
                rep.base_url + "/healthz", timeout=self.probe_timeout_s
            )
            if code != 200:
                raise OSError(f"/healthz returned {code}")
            _, stats = _http_json(
                rep.base_url + "/v1/stats", timeout=self.probe_timeout_s
            )
        except (OSError, HTTPException, ValueError) as e:
            self._note_failure(rep, f"probe: {type(e).__name__}: {e}", now)
            return
        with self._lock:
            rep.consecutive_failures = 0
            rep.slots = int(stats.get("slots") or 1)
            rep.slots_active = int(stats.get("slots_active") or 0)
            rep.queue_depth = int(stats.get("queue_depth") or 0)
            rep.prefix_hit_rate = float(
                stats.get("prefix_cache_hit_rate") or 0.0
            )
            rep.last_stats = dict(stats)
            rep.last_error = None
        engine_state = str(health.get("state") or "ready")
        if rep.state == "ejected":
            self.counters["readmissions"] += 1
            self._incr("router_readmissions_total")
            rep.eject_streak = 0
        if rep.state in ("draining",):
            # Drain status is router-owned; probes only refresh load.
            return
        self._set_state(
            rep, "draining" if engine_state == "draining" else (
                "ready" if engine_state == "ready" else "warming"
            )
        )

    def note_request_failure(self, rep: Replica, error: str) -> None:
        """A proxied request failed at the connection level — counts
        toward ejection exactly like a failed probe."""
        self._note_failure(rep, error, time.time())

    def _note_failure(self, rep: Replica, error: str, now: float) -> None:
        with self._lock:
            rep.last_error = error
            rep.consecutive_failures += 1
            failures = rep.consecutive_failures
            was_ejected = rep.state == "ejected"
        if was_ejected:
            # A failed re-admission probe: double the backoff window.
            with self._lock:
                rep.eject_streak += 1
                rep.ejected_until = now + min(
                    self.eject_backoff_max_s,
                    self.eject_backoff_s * (2 ** rep.eject_streak),
                )
            return
        if rep.state == "warming":
            # A replica that was NEVER ready isn't "ejected" — it is
            # still booting (probes hit a socket nobody listens on
            # yet).  It stays warming (clients see 503 "warming", not
            # "unavailable") and keeps being probed every interval.
            return
        if failures >= self.eject_failures and rep.state != "drained":
            with self._lock:
                rep.eject_streak = 0
                rep.ejected_until = now + self.eject_backoff_s
            self.counters["ejections"] += 1
            self._incr("router_ejections_total")
            self._set_state(rep, "ejected")

    # -- drain ----------------------------------------------------------------
    def drain(self, name: str, deadline_s: Optional[float] = None) -> bool:
        """Stop routing to ``name``; in-flight requests finish (bounded
        by ``deadline_s``).  Returns False for unknown replicas."""
        rep = self.replica(name)
        if rep is None:
            return False
        now = time.time()
        with self._lock:
            rep.drain_started = now
            rep.drain_deadline = (
                now + deadline_s if deadline_s is not None else None
            )
        self.counters["drains"] += 1
        self._incr("router_drains_total")
        self._set_state(rep, "draining")
        return True

    def is_drained(self, name: str) -> bool:
        rep = self.replica(name)
        return rep is not None and rep.state == "drained"

    def _advance_drains(self, now: float) -> None:
        for name in self.replica_names():
            rep = self.replica(name)
            if rep is None or rep.state != "draining":
                continue
            timed_out = (
                rep.drain_deadline is not None and now > rep.drain_deadline
            )
            idle = (
                rep.inflight == 0
                and rep.slots_active == 0
                and rep.queue_depth == 0
            )
            # An unreachable draining replica is as drained as it will
            # ever get — don't wait the full deadline on a corpse.
            if idle and rep.consecutive_failures >= self.eject_failures:
                timed_out = True
            if idle and rep.drain_started is not None:
                # Require one probe newer than the drain start so a
                # stale pre-drain stats snapshot can't declare victory.
                if rep.last_probe_at <= rep.drain_started and not timed_out:
                    continue
            if idle or timed_out:
                self._set_state(rep, "drained")
                cb = self.on_drained
                if cb is not None:
                    try:
                        cb(rep.name, timed_out and not idle)
                    except Exception:  # pragma: no cover - callback guard
                        pass

    # -- selection ------------------------------------------------------------
    def _prefix_key(self, prompt: Sequence[int]) -> Optional[bytes]:
        if self.affinity_tokens <= 0 or not prompt:
            return None
        head = ",".join(str(int(t)) for t in prompt[: self.affinity_tokens])
        return head.encode()

    def _affine(
        self, prompt: Sequence[int], ready: List[Replica]
    ) -> Optional[Replica]:
        """Rendezvous hash of the prompt prefix over the ready set —
        stable under membership churn (losing a replica only remaps the
        keys that pointed at it)."""
        key = self._prefix_key(prompt)
        if key is None:
            return None
        best, best_score = None, b""
        for rep in ready:
            score = hashlib.md5(key + b"|" + rep.name.encode()).digest()
            if best is None or score > best_score:
                best, best_score = rep, score
        return best

    def select(
        self,
        prompt: Sequence[int],
        exclude: Optional[set] = None,
    ) -> Replica:
        """Pick a replica for ``prompt`` (and count it in-flight), or
        raise a typed :class:`RouterError`:

        - 503 ``warming`` — replicas exist but none has reached ready
          (a booting fleet is not overloaded — clients should not back
          off the way a 429 tells them to);
        - 503 ``no_replicas`` — the fleet is EMPTY of live capacity:
          no replicas at all, or every replica ejected/dead/drained.
          Distinct from the retry-exhausted 502 ``upstream_error``
          (requests were attempted and failed) — here nothing was ever
          attemptable;
        - 503 ``unavailable`` — no ready replica right now, but at
          least one is draining (in-flight work still finishing);
        - 429 ``overloaded`` — fleet-mean occupancy at/over the ceiling.
        """
        exclude = exclude or set()
        with self._lock:
            candidates = [
                r
                for r in self._replicas.values()
                if r.name not in exclude
            ]
            ready = [r for r in candidates if r.state == "ready"]
            if not ready:
                if not candidates or all(
                    r.state in ("ejected", "dead", "drained")
                    for r in candidates
                ):
                    raise RouterError(
                        "no_replicas",
                        "fleet has no live replicas"
                        + (
                            " (all ejected, dead, or drained)"
                            if candidates
                            else ""
                        ),
                        status=503,
                    )
                if any(r.state == "warming" for r in candidates):
                    raise RouterError(
                        "warming",
                        "all replicas are still warming",
                        status=503,
                        retry_after_s=self.retry_after_s,
                    )
                raise RouterError(
                    "unavailable",
                    "no ready replica (draining in progress)",
                    status=503,
                    retry_after_s=self.retry_after_s,
                )
            fleet_load = sum(min(1.0, r.load()) for r in ready) / len(ready)
            if fleet_load >= self.shed_occupancy:
                self.counters["sheds"] += 1
                self._incr("router_sheds_total")
                raise RouterError(
                    "overloaded",
                    f"fleet occupancy {fleet_load:.2f} >= "
                    f"{self.shed_occupancy:.2f} (request shed)",
                    status=429,
                    retry_after_s=self.retry_after_s,
                )
            affine = self._affine(prompt, ready)
            rep = min(ready, key=lambda r: r.load())
            if affine is not None and affine.load() < 1.0:
                # Prefix-hit-aware affinity: a warm-but-busy affine
                # replica is worth routing into only in proportion to
                # how warm it actually is — its probed prefix_hit_rate
                # buys extra slack over the least-loaded alternative
                # (a cold replica gets only the base slack, so affinity
                # can still bootstrap a cache).
                slack = (
                    self.affinity_slack
                    + affine.prefix_hit_rate * self.affinity_hit_slack
                )
                if affine.load() - rep.load() <= slack:
                    rep = affine
            rep.inflight += 1
            rep.requests += 1
            return rep

    # -- request proxying ------------------------------------------------------
    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> Dict[str, Any]:
        """Proxy one ``/generate`` call with bounded failover.

        All prompts in the call land on ONE replica (affinity is keyed
        on the first prompt).  Connection-level failures fail over to a
        different replica up to ``retry_limit`` times; replica HTTP
        errors come back as typed :class:`RouterError`.  The response
        dict gains ``replica`` and ``retries`` keys.

        When tracing is on, ``trace`` (an inbound client context) or a
        fresh trace id covers the WHOLE call: one ``router.request``
        root span, one ``router.attempt`` child per upstream try — so a
        failover shows every attempt on the same timeline — and the
        traceparent is injected on each hop so replica-side spans join
        the trace.  The response's ``trace`` block gains the trace id.
        """
        timeout = timeout_s if timeout_s is not None else self.request_timeout_s
        payload: Dict[str, Any] = {
            "prompts": [list(p) for p in prompts],
            "temperature": temperature,
        }
        if max_new_tokens is not None:
            payload["max_new_tokens"] = max_new_tokens
        self.counters["requests"] += 1
        self._incr("router_requests_total")
        ctx: Optional[TraceContext] = None
        if self.trace_requests:
            ctx = trace if trace is not None else TraceContext(new_trace_id())
            if not ctx.sampled:
                ctx = None
        if ctx is None:
            return self._attempt_loop(prompts, payload, timeout, None)
        with get_tracer().span(
            "router.request",
            sample=1.0,
            trace_id=ctx.trace_id,
            parent_id=ctx.span_id or None,
            process="router",
            prompts=len(prompts),
        ) as root:
            body = self._attempt_loop(
                prompts, payload, timeout, ctx.child(root.span_id)
            )
        trace_block = body.setdefault("trace", {})
        trace_block["trace_id"] = ctx.trace_id
        return body

    def _attempt_loop(
        self,
        prompts: Sequence[Sequence[int]],
        payload: Dict[str, Any],
        timeout: float,
        ctx: Optional[TraceContext],
    ) -> Dict[str, Any]:
        """The bounded-failover loop behind :meth:`generate`.

        ``ctx``, when given, is parented to the ``router.request`` root
        span; each try wraps its upstream hop in a ``router.attempt``
        span and injects a context parented to THAT span, so the merged
        timeline nests client → router → attempt → replica.
        """
        tracer = get_tracer()
        tried: set = set()
        last_error = "no attempt made"
        for attempt in range(self.retry_limit + 1):
            try:
                rep = self.select(prompts[0] if prompts else (), exclude=tried)
            except RouterError as e:
                if tried and e.kind in ("no_replicas", "unavailable", "warming"):
                    # Nothing left to fail over to: report the FAULT
                    # (what broke the attempts), not the empty set the
                    # exclusions produced.
                    raise RouterError(
                        "upstream_error",
                        f"all {len(tried)} attempted replica(s) failed "
                        f"(last: {last_error})",
                        status=502,
                    )
                raise
            try:
                headers: Dict[str, str] = {}
                if ctx is not None:
                    with tracer.span(
                        "router.attempt",
                        sample=1.0,
                        trace_id=ctx.trace_id,
                        parent_id=ctx.span_id or None,
                        process="router",
                        replica=rep.name,
                        attempt=attempt,
                    ) as asp:
                        inject(ctx.child(asp.span_id), headers)
                        code, body = _http_json(
                            rep.base_url + "/generate",
                            payload,
                            timeout=timeout,
                            headers=headers,
                        )
                        asp.set(status=code)
                else:
                    code, body = _http_json(
                        rep.base_url + "/generate", payload, timeout=timeout
                    )
            except socket.timeout:
                # The replica is alive but slow — retrying elsewhere
                # would double the load that made it slow.
                raise RouterError(
                    "upstream_timeout",
                    f"replica {rep.name} exceeded {timeout:.0f}s",
                    status=504,
                )
            except (OSError, HTTPException, ValueError) as e:
                # Connection refused/reset, mid-response death: the
                # client saw nothing, so replay on another replica.
                tried.add(rep.name)
                last_error = f"{type(e).__name__}: {e}"
                self.note_request_failure(rep, last_error)
                self.counters["retries"] += 1
                self._incr("router_retries_total")
                continue
            finally:
                with self._lock:
                    rep.inflight = max(0, rep.inflight - 1)
            if code == 200:
                if attempt > 0:
                    self.counters["failovers"] += 1
                    self._incr("router_failovers_total")
                body["replica"] = rep.name
                body["retries"] = attempt
                return body
            err = body.get("error") or {}
            if not isinstance(err, dict):
                err = {"kind": "upstream_error", "message": str(err)}
            kind = str(err.get("kind") or "upstream_error")
            if code == 429:
                # The ENGINE shed (pool exhaustion) — propagate the
                # typed 429 verbatim; it is load signal, not a fault.
                self.counters["sheds"] += 1
                self._incr("router_sheds_total")
                raise RouterError(
                    "shed",
                    str(err.get("message") or "request shed by replica"),
                    status=429,
                    retry_after_s=self.retry_after_s,
                )
            self.counters["upstream_errors"] += 1
            self._incr("router_upstream_errors_total")
            raise RouterError(
                kind,
                f"replica {rep.name}: "
                f"{err.get('message') or f'HTTP {code}'}",
                status=502 if code >= 500 else code,
            )
        raise RouterError(
            "upstream_error",
            f"all {len(tried)} attempted replica(s) failed "
            f"(last: {last_error})",
            status=502,
        )

    # -- introspection ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            reps = {name: r.snapshot() for name, r in self._replicas.items()}
        by_state: Dict[str, int] = {}
        for r in reps.values():
            by_state[r["state"]] = by_state.get(r["state"], 0) + 1
        requests = self.counters["requests"]
        return {
            "replicas": reps,
            "by_state": by_state,
            "n_ready": by_state.get("ready", 0),
            "counters": dict(self.counters),
            "shed_rate": (
                round(self.counters["sheds"] / requests, 4) if requests else 0.0
            ),
            "shed_occupancy": self.shed_occupancy,
        }

    def replica_stats(self) -> Dict[str, Dict[str, Any]]:
        """Each replica's full ``/v1/stats`` body from its last
        successful probe — the scrape phase's per-replica series source
        (no new connections; a never-probed replica is absent)."""
        with self._lock:
            return {
                name: dict(r.last_stats)
                for name, r in self._replicas.items()
                if r.last_stats
            }

    def merged_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """All spans of one trace, fleet-wide, as a Perfetto-loadable dict.

        Merges the router's own ring buffer with each replica's
        ``GET /v1/trace/<trace_id>`` response; the chrome-trace export
        keys process rows by span ``process`` label, so router and every
        replica land on distinct named tracks of one timeline.  Returns
        None when no process holds any span for the id (expired from
        the ring buffers, or never sampled).
        """
        spans = [
            s
            for s in get_tracer().spans()
            if s.get("trace_id") == trace_id
        ]
        with self._lock:
            urls = [r.base_url for r in self._replicas.values()]
        for base_url in urls:
            try:
                code, body = _http_json(
                    base_url + "/v1/trace/" + trace_id,
                    timeout=self.probe_timeout_s,
                )
            except (OSError, HTTPException, ValueError):
                continue  # a dead replica must not break the merge
            if code == 200 and isinstance(body.get("spans"), list):
                spans.extend(body["spans"])
        if not spans:
            return None
        spans.sort(key=lambda s: s.get("start", 0.0))
        return {
            "trace_id": trace_id,
            "spans": spans,
            "chrome_trace": chrome_trace(spans),
        }

    # -- stats plumbing --------------------------------------------------------
    def _incr(self, key: str) -> None:
        try:
            self.metrics.incr(key)
        except Exception:  # pragma: no cover - stats must never raise
            pass

    def _set_state(self, rep: Replica, state: str) -> None:
        with self._lock:
            rep.state = state
        try:
            self.metrics.gauge(
                labeled_key("fleet_replica_state", replica=rep.name),
                float(_STATE_CODE.get(state, -1)),
            )
        except Exception:  # pragma: no cover - stats must never raise
            pass


def make_router_handler(router: FleetRouter, meta: Optional[dict] = None):
    """HTTP front-end over a :class:`FleetRouter` — the same route shape
    as ``lm_server`` so clients cannot tell one replica from a fleet:
    ``POST /generate``, ``GET /healthz``, ``GET /v1/stats``,
    ``GET /metrics``.  Typed errors carry ``error.kind`` and shed
    responses carry ``Retry-After``."""
    import json as json_mod
    from http.server import BaseHTTPRequestHandler

    meta = meta or {}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _json(self, code, payload, headers=None):
            body = json_mod.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _router_error(self, e: RouterError):
            headers = {}
            if e.retry_after_s is not None:
                headers["Retry-After"] = str(int(max(1, e.retry_after_s)))
            return self._json(e.status, e.payload(), headers)

        def do_GET(self):
            if self.path == "/v1/stats":
                return self._json(200, router.stats())
            if self.path.startswith("/v1/trace/"):
                trace_id = self.path[len("/v1/trace/"):]
                merged = router.merged_trace(trace_id) if trace_id else None
                if merged is None:
                    return self._json(
                        404,
                        {
                            "error": {
                                "kind": "not_found",
                                "message": f"no spans for trace {trace_id!r}",
                            }
                        },
                    )
                return self._json(200, merged)
            if self.path == "/metrics":
                from polyaxon_tpu.stats.metrics import (
                    PROMETHEUS_CONTENT_TYPE,
                    render_prometheus,
                    render_standard_gauges,
                )

                snapshot_fn = getattr(router.metrics, "snapshot", None)
                if snapshot_fn is None:
                    text = "# router stats backend keeps no registry\n"
                else:
                    try:
                        snap = snapshot_fn(include_timings=False)
                    except TypeError:  # duck-typed stand-in without the kwarg
                        snap = snapshot_fn()
                    text = render_prometheus(
                        snap, labels={"component": "router"}
                    )
                text += render_standard_gauges(labels={"component": "router"})
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                return self.wfile.write(body)
            if self.path not in ("/healthz", "/"):
                return self._json(
                    404, {"error": {"kind": "not_found", "message": "not found"}}
                )
            st = router.stats()
            state = (
                "ready"
                if st["n_ready"]
                else "warming" if st["by_state"].get("warming") else "unavailable"
            )
            return self._json(
                200,
                {
                    "ok": bool(st["n_ready"]),
                    "state": state,
                    "fleet": st["by_state"],
                    **meta,
                },
            )

        def do_POST(self):
            if self.path != "/generate":
                return self._json(
                    404, {"error": {"kind": "not_found", "message": "not found"}}
                )
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json_mod.loads(self.rfile.read(n) or b"{}")
                prompts = req["prompts"]
                if not prompts or not isinstance(prompts[0], list):
                    raise ValueError("prompts must be a list of id lists")
                max_new = req.get("max_new_tokens")
                temperature = float(req.get("temperature", 0.0))
            except (KeyError, ValueError, TypeError) as e:
                return self._json(
                    400, {"error": {"kind": "bad_request", "message": str(e)}}
                )
            try:
                body = router.generate(
                    prompts,
                    int(max_new) if max_new is not None else None,
                    temperature,
                    # Malformed/missing traceparent → None → fresh trace;
                    # a client header must never turn into a 500.
                    trace=extract(self.headers),
                )
            except RouterError as e:
                return self._router_error(e)
            return self._json(200, body)

    return Handler
