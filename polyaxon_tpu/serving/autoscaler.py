"""Fleet autoscaler: shed/occupancy-driven ``N → N±1`` serving resizes.

PR 14 gave the fleet hands — drain, replace, failover — and the
remediation engine a budget; this module is the closed loop that
*changes N* without an operator.  A :class:`FleetAutoscaler` rides the
same thread-free pump as :meth:`ServingFleet.poll` (ultimately the
scheduler's monitor tick): every ``evaluate()`` samples the router's
lifetime counters and per-replica occupancy, and drives exactly one
resize operation at a time through the fleet's own machinery:

- **scale-up** — the windowed shed fraction (Δsheds/Δrequests between
  ticks) holds at/above ``POLYAXON_TPU_AUTOSCALER_SHED_RATE`` for
  ``UP_HOLD_S``: submit one replica through the fleet's registry-run
  path (``fleet.scale_up()``).  The decision only *succeeds* when the
  router's probe machinery walks the newcomer through ``warming →
  ready`` — a submitted-but-stuck replica FAILs the decision at the
  fleet ready timeout and is retired, so the autoscaler never counts
  capacity the router cannot route to.
- **drain-down** — fleet-mean ready occupancy holds below
  ``IDLE_OCCUPANCY`` (with zero sheds in the window) for
  ``DOWN_HOLD_S``: drain the *idlest* ready replica via the PR 14
  drain path (router stops routing, in-flight requests finish bounded
  by the fleet drain deadline), then retire it.  Never below
  ``MIN_REPLICAS``.
- **capacity repair** — membership fell below the committed target (a
  replica died and the fleet reaped it): submit a replacement without
  waiting for a shed signal, because when nothing is ready there are
  no sheds to rate.  Repair respects only the up-cooldown (bounding
  crash-loop churn) and the budget.

Oscillation control is layered: *hysteresis* (the signal must hold,
not spike), *per-direction cooldowns* (``UP_COOLDOWN_S`` /
``DOWN_COOLDOWN_S``), and *flap suppression* (a completed scale-up
re-arms the down cooldown, so the capacity just added cannot be
drained by the quiet moment it created; scale-up after a drain-down
stays fast — availability beats parsimony).  The remediation budget is
a hard cap: once ``BUDGET`` non-skipped decisions have fired the
autoscaler records one SKIPPED row and goes inert.

Every decision is a ``scale_up`` / ``scale_down`` remediation row on
the affected replica's run (phases ``submitted → ready`` /
``draining → stopped`` on the timeline), an
``autoscaler_decision_total{direction,outcome}`` counter, and a
``fleet_target_replicas`` gauge — the same observability contract as
every other control-plane reflex.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_int
from polyaxon_tpu.db.registry import RemediationStatus
from polyaxon_tpu.stats.tsdb import RatioWindow

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Evaluates resize decisions for one fleet; strictly thread-free.

    ``fleet`` must provide the resize protocol both fleet classes
    implement: ``router``, ``scale_up() -> name``,
    ``retire_replica(name)``, ``run_id_for(name) -> Optional[int]``,
    and optionally ``registry`` (remediation rows are skipped without
    one — the :class:`LocalServingFleet` chaos harness has no control
    plane).  Constructor arguments override the
    ``POLYAXON_TPU_AUTOSCALER_*`` knob catalog, test-style.
    """

    def __init__(
        self,
        fleet: Any,
        *,
        enabled: Optional[bool] = None,
        shed_rate: Optional[float] = None,
        idle_occupancy: Optional[float] = None,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        up_hold_s: Optional[float] = None,
        down_hold_s: Optional[float] = None,
        up_cooldown_s: Optional[float] = None,
        down_cooldown_s: Optional[float] = None,
        budget: Optional[int] = None,
        ready_timeout_s: Optional[float] = None,
        drain_deadline_s: Optional[float] = None,
    ) -> None:
        self.fleet = fleet
        self.router = fleet.router
        self.enabled = (
            enabled
            if enabled is not None
            else knob_bool("POLYAXON_TPU_AUTOSCALER_ENABLED")
        )
        self.shed_rate = (
            shed_rate
            if shed_rate is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_SHED_RATE")
        )
        self.idle_occupancy = (
            idle_occupancy
            if idle_occupancy is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_IDLE_OCCUPANCY")
        )
        self.min_replicas = (
            min_replicas
            if min_replicas is not None
            else knob_int("POLYAXON_TPU_AUTOSCALER_MIN_REPLICAS")
        )
        self.max_replicas = (
            max_replicas
            if max_replicas is not None
            else knob_int("POLYAXON_TPU_AUTOSCALER_MAX_REPLICAS")
        )
        self.up_hold_s = (
            up_hold_s
            if up_hold_s is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_UP_HOLD_S")
        )
        self.down_hold_s = (
            down_hold_s
            if down_hold_s is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_DOWN_HOLD_S")
        )
        self.up_cooldown_s = (
            up_cooldown_s
            if up_cooldown_s is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_UP_COOLDOWN_S")
        )
        self.down_cooldown_s = (
            down_cooldown_s
            if down_cooldown_s is not None
            else knob_float("POLYAXON_TPU_AUTOSCALER_DOWN_COOLDOWN_S")
        )
        if budget is None:
            budget = knob_int("POLYAXON_TPU_AUTOSCALER_BUDGET")
            if budget <= 0:
                budget = knob_int("POLYAXON_TPU_REMEDIATION_BUDGET")
        self.budget = budget
        self.ready_timeout_s = (
            ready_timeout_s
            if ready_timeout_s is not None
            else getattr(
                fleet,
                "ready_timeout_s",
                knob_float("POLYAXON_TPU_FLEET_READY_TIMEOUT_S"),
            )
        )
        self.drain_deadline_s = (
            drain_deadline_s
            if drain_deadline_s is not None
            else getattr(
                fleet,
                "drain_deadline_s",
                knob_float("POLYAXON_TPU_FLEET_DRAIN_DEADLINE_S"),
            )
        )
        self.fleet_name = str(getattr(fleet, "name", "local"))
        #: Windowed sheds/requests counter pair — rates are taken over a
        #: short smoothing window, not a single tick (sparse traffic
        #: would otherwise zero the rate on every empty tick).  Shared
        #: code path with the SLO burn windows (stats.tsdb).
        self._shed_window = RatioWindow(self.up_hold_s / 2.0)
        self._window_req = 0
        #: When the current overload / idle episode started (None = the
        #: signal is not holding).
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._last_up_at = 0.0
        self._last_down_at = 0.0
        #: The one in-flight resize operation (decisions serialize).
        self._op: Optional[Dict[str, Any]] = None
        self.decisions_spent = 0
        self._budget_skip_recorded = False
        self.last_decision: Optional[Dict[str, Any]] = None
        #: Last tick's observed signals, for status()/the health probe.
        self.last_shed_rate = 0.0
        self.last_occupancy = 0.0
        self.target: Optional[int] = None

    # -- plumbing -------------------------------------------------------------
    @property
    def _registry(self) -> Any:
        orch = getattr(self.fleet, "orch", None)
        return getattr(orch, "registry", None)

    def _count(self, direction: str, outcome: str) -> None:
        from polyaxon_tpu.stats.metrics import labeled_key

        try:
            self.router.metrics.incr(
                labeled_key(
                    "autoscaler_decision_total",
                    direction=direction,
                    outcome=outcome,
                )
            )
        except Exception:  # pragma: no cover - stats must never raise
            pass

    def _gauge_target(self) -> None:
        from polyaxon_tpu.stats.metrics import labeled_key

        try:
            self.router.metrics.gauge(
                labeled_key("fleet_target_replicas", fleet=self.fleet_name),
                float(self.target if self.target is not None else 0),
            )
        except Exception:  # pragma: no cover - stats must never raise
            pass

    def _add_row(
        self, name: str, action: str, status: str, message: str, **attrs: Any
    ) -> Optional[int]:
        """One remediation row on the replica run's timeline (None when
        the fleet has no registry or the replica no run)."""
        registry = self._registry
        if registry is None:
            return None
        run_id = self.fleet.run_id_for(name)
        if run_id is None:
            return None
        try:
            row = registry.add_remediation(
                run_id,
                action,
                trigger="autoscaler",
                status=status,
                message=message,
                attrs=attrs,
            )
            return row["id"]
        except Exception:  # pragma: no cover - rows are best-effort
            return None

    def _update_row(self, op: Dict[str, Any], **kwargs: Any) -> None:
        registry = self._registry
        rem_id = op.get("rem_id")
        if registry is None or rem_id is None:
            return
        try:
            registry.update_remediation(rem_id, **kwargs)
        except Exception:  # pragma: no cover - rows are best-effort
            pass

    # -- signals --------------------------------------------------------------
    def _membership(self) -> int:
        """Replicas the fleet currently owns (any routable state —
        a warming newcomer already counts toward the ceiling)."""
        return sum(
            1
            for n in self.router.replica_names()
            if (r := self.router.replica(n)) is not None
            and r.state not in ("drained", "dead")
        )

    def _observe(self, now: float) -> None:
        """Fold the windowed counter deltas and occupancy into the
        hysteresis timers.

        The shed rate is taken over the trailing half-up-hold window,
        not a single tick: at pump cadence most ticks see zero requests
        on a lightly loaded fleet, and a per-tick rate would reset the
        overload episode on every empty tick, so the hold could never
        be satisfied by sparse (but persistently shedding) traffic.  A
        tick whose window saw no requests at all is no evidence either
        way and leaves the episode timer untouched — the idle branch
        (occupancy near zero, no sheds) is what ends an episode when
        traffic stops entirely.
        """
        counters = self.router.counters
        requests = int(counters.get("requests", 0))
        sheds = int(counters.get("sheds", 0))
        window_s = self.up_hold_s / 2.0
        self._shed_window.observe(sheds, requests, now)
        deltas = self._shed_window.deltas(window_s, now)
        if deltas is None:
            # First tick: no interval to rate over.
            return
        d_shed, d_req = deltas
        self._window_req = int(d_req)
        self.last_shed_rate = (d_shed / d_req) if d_req > 0 else 0.0

        with self.router._lock:
            ready_loads = [
                r.load()
                for r in self.router._replicas.values()
                if r.state == "ready"
            ]
        self.last_occupancy = (
            sum(min(1.0, x) for x in ready_loads) / len(ready_loads)
            if ready_loads
            else 0.0
        )

        if d_req > 0:
            if self.last_shed_rate >= self.shed_rate:
                if self._up_since is None:
                    self._up_since = now
            else:
                self._up_since = None

        idle = (
            bool(ready_loads)
            and self.last_occupancy < self.idle_occupancy
            and d_shed == 0
        )
        if idle:
            if self._down_since is None:
                self._down_since = now
            self._up_since = None  # a quiet fleet is not overloaded
        else:
            self._down_since = None

    # -- decisions ------------------------------------------------------------
    def _budget_ok(self, direction: str, now: float) -> bool:
        if self.decisions_spent < self.budget:
            return True
        if not self._budget_skip_recorded:
            self._budget_skip_recorded = True
            self.last_decision = {
                "direction": direction,
                "outcome": "skipped",
                "reason": f"budget ({self.budget}) exhausted",
                "at": now,
            }
            self._count(direction, "skipped")
            # The skip itself goes on a timeline when one exists — pin
            # it to any current member so the refusal is visible.
            names = self.router.replica_names()
            if names:
                self._add_row(
                    names[0],
                    f"scale_{direction}",
                    RemediationStatus.SKIPPED,
                    f"autoscaler budget ({self.budget}) exhausted",
                    signal="budget",
                )
        return False

    def _start_scale_up(self, now: float, reason: str = "shed") -> None:
        if not self._budget_ok("up", now):
            return
        try:
            name = self.fleet.scale_up()
        except Exception as exc:
            self._last_up_at = now  # cooldown a failing submit path too
            self.last_decision = {
                "direction": "up",
                "outcome": "failed",
                "reason": f"scale_up failed: {exc}",
                "at": now,
            }
            self._count("up", "failed")
            return
        self.decisions_spent += 1
        if reason == "repair":
            message = (
                f"membership fell below target {self.target} "
                f"(replica lost) — submitted replacement {name}"
            )
        else:
            message = (
                f"shed rate {self.last_shed_rate:.2f} >= "
                f"{self.shed_rate:.2f} held {self.up_hold_s:.0f}s — "
                f"submitted replica {name}"
            )
            self.target = self._membership()
            self._gauge_target()
        rem_id = self._add_row(
            name,
            "scale_up",
            RemediationStatus.IN_PROGRESS,
            message,
            phase="submitted",
            signal=reason,
            shed_rate=round(self.last_shed_rate, 4),
            target_replicas=self.target,
        )
        self._op = {
            "direction": "up",
            "name": name,
            "rem_id": rem_id,
            "deadline": now + self.ready_timeout_s,
        }
        self._up_since = None
        self.last_decision = {
            "direction": "up",
            "outcome": "started",
            "replica": name,
            "shed_rate": round(self.last_shed_rate, 4),
            "at": now,
        }
        self._count("up", "started")

    def _start_scale_down(self, now: float) -> None:
        with self.router._lock:
            ready = [
                r
                for r in self.router._replicas.values()
                if r.state == "ready"
            ]
            victim = min(ready, key=lambda r: (r.load(), r.name)) if ready else None
        if victim is None:
            return
        if not self._budget_ok("down", now):
            return
        self.decisions_spent += 1
        self.router.drain(victim.name, deadline_s=self.drain_deadline_s)
        self.target = max(self.min_replicas, self._membership() - 1)
        self._gauge_target()
        rem_id = self._add_row(
            victim.name,
            "scale_down",
            RemediationStatus.IN_PROGRESS,
            f"fleet-mean occupancy {self.last_occupancy:.2f} < "
            f"{self.idle_occupancy:.2f} held {self.down_hold_s:.0f}s — "
            f"draining idlest replica {victim.name}",
            phase="draining",
            occupancy=round(self.last_occupancy, 4),
            target_replicas=self.target,
        )
        self._op = {
            "direction": "down",
            "name": victim.name,
            "rem_id": rem_id,
            "deadline": now + self.drain_deadline_s + self.ready_timeout_s,
        }
        self._down_since = None
        self.last_decision = {
            "direction": "down",
            "outcome": "started",
            "replica": victim.name,
            "occupancy": round(self.last_occupancy, 4),
            "at": now,
        }
        self._count("down", "started")

    # -- op advancement -------------------------------------------------------
    def _advance_op(self, now: float) -> None:
        op = self._op
        if op is None:
            return
        name = op["name"]
        rep = self.router.replica(name)
        if op["direction"] == "up":
            if rep is not None and rep.state == "ready":
                self._update_row(
                    op,
                    status=RemediationStatus.SUCCEEDED,
                    message=f"replica {name} probed ready",
                    attrs={"phase": "ready"},
                )
                self._op = None
                self._last_up_at = now
                # Flap suppression: the quiet window the new capacity
                # just created must not immediately drain it.
                self._last_down_at = max(self._last_down_at, now)
                self._down_since = None
                self.last_decision = {
                    "direction": "up",
                    "outcome": "succeeded",
                    "replica": name,
                    "at": now,
                }
                self._count("up", "succeeded")
            elif now >= op["deadline"] or rep is None:
                # Missed the ready gate (or vanished): retire the stuck
                # submission so target and membership re-converge.
                try:
                    self.fleet.retire_replica(name)
                except Exception:
                    pass
                self._update_row(
                    op,
                    status=RemediationStatus.FAILED,
                    message=(
                        f"replica {name} missed the "
                        f"{self.ready_timeout_s:.0f}s ready deadline"
                    ),
                    attrs={"phase": "failed"},
                )
                self._op = None
                self._last_up_at = now
                self.target = self._membership()
                self._gauge_target()
                self.last_decision = {
                    "direction": "up",
                    "outcome": "failed",
                    "replica": name,
                    "at": now,
                }
                self._count("up", "failed")
            return
        # direction == "down"
        drained = rep is None or rep.state == "drained"
        if not drained and now < op["deadline"]:
            return
        try:
            self.fleet.retire_replica(name)
        except Exception:
            pass
        self._update_row(
            op,
            status=RemediationStatus.SUCCEEDED,
            message=f"replica {name} drained and stopped",
            attrs={"phase": "stopped"},
        )
        self._op = None
        self._last_down_at = now
        self.target = self._membership()
        self._gauge_target()
        self.last_decision = {
            "direction": "down",
            "outcome": "succeeded",
            "replica": name,
            "at": now,
        }
        self._count("down", "succeeded")

    # -- the tick -------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> None:
        """One autoscaler tick: sample signals, advance the in-flight
        operation, start at most one new decision.  Called from the
        fleet's ``poll()`` — must never sleep or block."""
        now = now if now is not None else time.time()
        if self.target is None:
            self.target = max(self.min_replicas, self._membership())
            self._gauge_target()
        self._observe(now)
        self._advance_op(now)
        if not self.enabled or self._op is not None:
            return
        members = self._membership()
        # Capacity repair: membership fell below the committed target
        # (a replica died and was reaped).  Shed-rate can't form when
        # nothing is ready to shed, so repair doesn't wait for it —
        # only for the up-cooldown, which bounds crash-loop churn.
        floor = max(self.min_replicas, min(self.target, self.max_replicas))
        if members < floor:
            if now - self._last_up_at >= self.up_cooldown_s:
                self._start_scale_up(now, reason="repair")
            return
        if (
            self._up_since is not None
            and self._window_req > 0  # fresh evidence, not a stale episode
            and now - self._up_since >= self.up_hold_s
            and now - self._last_up_at >= self.up_cooldown_s
            and members < self.max_replicas
        ):
            self._start_scale_up(now)
            return
        if (
            self._down_since is not None
            and now - self._down_since >= self.down_hold_s
            and now - self._last_down_at >= self.down_cooldown_s
            and members > self.min_replicas
            and self.router.stats()["n_ready"] > self.min_replicas
        ):
            self._start_scale_down(now)

    # -- introspection --------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        op = self._op
        return {
            "enabled": self.enabled,
            "fleet": self.fleet_name,
            "state": (
                f"scaling_{op['direction']}" if op is not None else "idle"
            ),
            "target_replicas": self.target,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "shed_rate": round(self.last_shed_rate, 4),
            "shed_rate_threshold": self.shed_rate,
            "occupancy": round(self.last_occupancy, 4),
            "idle_occupancy": self.idle_occupancy,
            "budget": self.budget,
            "budget_remaining": max(0, self.budget - self.decisions_spent),
            "last_decision": dict(self.last_decision or {}) or None,
            "open_op": (
                {k: v for k, v in op.items() if k != "rem_id"}
                if op is not None
                else None
            ),
        }
