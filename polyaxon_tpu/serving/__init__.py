"""Continuous-batching LM serving (Orca-style iteration-level scheduling
over a vLLM-style paged KV cache).

The engine owns ONE fixed-shape block pool; requests hold per-sequence
block TABLES (data, never shapes), so admission, retirement, prefix
sharing, and chunked prefill all happen at decode-STEP granularity with
zero steady-state recompilation — a long generation never
head-of-line-blocks a short one, a long PROMPT never stalls the decode
batch, and identical prompt prefixes share ref-counted KV blocks.
``builtins/services.py:lm_server`` is the HTTP front-end; the engine
itself is front-end-agnostic.
"""

from polyaxon_tpu.serving.engine import (
    EngineDrainingError,
    GenerationRequest,
    NgramDrafter,
    ServingEngine,
    SlotAllocator,
)
from polyaxon_tpu.serving.paging import (
    BlockAllocator,
    HostKVTier,
    PrefixCache,
    truncate_table,
)


def __getattr__(name):
    # FleetAutoscaler lives behind a lazy import: the serving package
    # is imported by replica subprocesses that never autoscale, and the
    # autoscaler pulls in the knob catalog + router early otherwise.
    if name == "FleetAutoscaler":
        from polyaxon_tpu.serving.autoscaler import FleetAutoscaler

        return FleetAutoscaler
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "BlockAllocator",
    "EngineDrainingError",
    "FleetAutoscaler",
    "GenerationRequest",
    "HostKVTier",
    "NgramDrafter",
    "PrefixCache",
    "ServingEngine",
    "SlotAllocator",
    "truncate_table",
]
