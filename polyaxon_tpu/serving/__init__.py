"""Continuous-batching LM serving (Orca-style iteration-level scheduling).

The engine owns ONE fixed-shape, slot-addressed KV cache and admits or
retires requests at decode-STEP granularity — a long generation never
head-of-line-blocks a short one, and a freed slot is refilled from the
queue mid-flight.  ``builtins/services.py:lm_server`` is the HTTP
front-end; the engine itself is front-end-agnostic.
"""

from polyaxon_tpu.serving.engine import (
    GenerationRequest,
    ServingEngine,
    SlotAllocator,
)

__all__ = ["GenerationRequest", "ServingEngine", "SlotAllocator"]
