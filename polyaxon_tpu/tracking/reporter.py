"""Worker→control-plane reporting channel.

Parity: the reference's in-pod sidecar + client callbacks — metric POSTs
(``api/experiments/views.py:495-509`` via polyaxon-client), sidecar liveness
reconcile (``sidecar/sidecar/__main__.py:39-58``), log publisher
(``publisher/service.py``).  TPU-native: each gang process appends typed
JSON lines to its own file under the run's ``reports/`` dir; the control
plane's watcher tails those files into the registry.  Append-only files on
shared storage give the same at-least-once semantics with no broker.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from pathlib import Path
from typing import Any, Dict, Optional, Union


class Reporter:
    """Append-only typed-line writer, safe for one writer per file."""

    # Event types that must survive a host crash: lifecycle transitions
    # drive scheduling decisions, so they are fsynced to disk.  Anomaly
    # lines are fsynced too — they are rare and often immediately precede
    # the crash they describe.  Command/capture lines are rare (one per
    # bus command) and drive control-plane lifecycle roll-ups, so they
    # get the same durability.  Everything else (metrics/logs/spans) is
    # flushed to the OS only — losing the last few lines of telemetry on a
    # power cut is fine, but an fsync per metric line serializes the train
    # loop on disk latency.
    FSYNC_TYPES = ("status", "anomaly", "command", "capture")

    def __init__(
        self,
        path: Union[str, Path],
        process_id: int = 0,
        fsync_all: bool = False,
    ) -> None:
        self.path = Path(path)
        self.process_id = process_id
        self.fsync_all = fsync_all
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        #: Callbacks the heartbeat thread runs every beat — how the command
        #: mailbox gets polled without its own thread.  Must be cheap (the
        #: idle cost is one listdir of a usually-empty dir) and must not
        #: raise (guarded anyway: a hook failure must not kill heartbeats).
        self._beat_hooks: list = []

    def _emit(self, type_: str, **payload: Any) -> None:
        line = json.dumps({"type": type_, "ts": time.time(), **payload}, default=str)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync_all or type_ in self.FSYNC_TYPES:
                os.fsync(self._fh.fileno())

    # -- typed events ---------------------------------------------------------
    def status(self, status: str, message: Optional[str] = None) -> None:
        self._emit("status", status=status, message=message)

    def metric(self, values: Dict[str, Any], step: Optional[int] = None) -> None:
        self._emit("metric", values=values, step=step)

    def log(self, line: str) -> None:
        self._emit("log", line=line)

    def heartbeat(self) -> None:
        self._emit("heartbeat")

    def resources(self, values: Dict[str, Any]) -> None:
        """Telemetry samples (cpu/rss/HBM) — streamed like metrics."""
        self._emit("resources", values=values)

    def progress(
        self,
        *,
        step: Optional[int] = None,
        epoch: Optional[int] = None,
        throughput: Optional[float] = None,
        at: Optional[float] = None,
    ) -> None:
        """Forward-progress beacon relay (see tracking/flightrec.py).

        The watcher folds these into the registry's ``progress`` table —
        the gang-level stall/straggler detector's input.  ``at`` is the
        wall time of the *beat itself*: emission is throttled (and flushed
        once more at shutdown), so the line's own ``ts`` can postdate the
        progress it describes — stall ages must be measured from ``at``."""
        self._emit(
            "progress", step=step, epoch=epoch, throughput=throughput, at=at
        )

    def anomaly(
        self, kind: str, message: Optional[str] = None, **attrs: Any
    ) -> None:
        """A detected anomaly (stall, crash) with its forensic context —
        typically the path of a flight-recorder dump in ``attrs['dump']``."""
        self._emit("anomaly", kind=kind, message=message, **attrs)

    def span(self, record: Dict[str, Any]) -> None:
        """Ship a finished tracer span (see tracking/trace.py) upstream.

        Wired as the worker tracer's sink; the watcher ingests these into
        the registry's ``spans`` table for the cross-process timeline."""
        self._emit("span", **record)

    def ledger(self, record: Dict[str, Any]) -> None:
        """Ship a utilization-ledger row (see tracking/ledger.py) upstream.

        Wired as the worker ledger's sink; the watcher ingests these into
        the registry's ``utilization`` table for the run's goodput/MFU
        roll-up."""
        self._emit("ledger", **record)

    def service(
        self, *, url: Optional[str] = None, query: Optional[str] = None
    ) -> None:
        """Advertise (or refine) this run's service URL.

        ``url`` replaces the dispatch-recorded URL outright; ``query``
        appends a query string to it — how jupyter publishes its access
        token without the control plane ever knowing it ahead of time."""
        self._emit("service", url=url, query=query)

    def command_event(
        self,
        uuid: str,
        state: str,
        message: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Report this process's lifecycle state for a bus command
        (acked/complete/failed) — the watcher folds these into the
        registry's ``commands`` roll-up."""
        self._emit("command", uuid=uuid, state=state, message=message, **attrs)

    def capture(self, record: Dict[str, Any]) -> None:
        """Ship an on-demand profiling capture record (see
        tracking/capture.py) upstream — the watcher ingests these into the
        registry's ``captures`` table (one latest-wins row per host)."""
        self._emit("capture", **record)

    def error(self, exc: BaseException) -> None:
        self._emit(
            "status",
            status="failed",
            message=f"{type(exc).__name__}: {exc}",
            traceback=traceback.format_exc(),
        )

    # -- heartbeat thread -----------------------------------------------------
    def add_beat_hook(self, hook) -> None:
        """Run ``hook()`` on the heartbeat thread every beat interval.

        The command-bus mailbox poll rides here: the heartbeat cadence is
        already the worker's control-plane contact rhythm, so command
        delivery costs no extra thread and no extra wakeups."""
        self._beat_hooks.append(hook)

    def _run_beat_hooks(self) -> None:
        for hook in self._beat_hooks:
            try:
                hook()
            except Exception:
                # A broken hook must not take the liveness signal with it.
                pass

    def start_heartbeat(self, interval: float) -> None:
        if self._hb_thread is not None or interval <= 0:
            return
        self.heartbeat()  # immediate first beat: no zombie window at startup
        self._run_beat_hooks()

        def beat() -> None:
            while not self._hb_stop.wait(interval):
                self.heartbeat()
                self._run_beat_hooks()

        self._hb_thread = threading.Thread(target=beat, name="heartbeat", daemon=True)
        self._hb_thread.start()

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None
        with self._lock:
            self._fh.close()
