"""The run context handed to user entrypoints.

Parity: the reference's in-job ``polyaxon-client`` helper (experiment
tracking: metrics, outputs paths, cluster info) — here extended with the
TPU-native runtime objects: the device mesh, the parallelism strategy, and
first-class checkpoint paths.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional

from polyaxon_tpu.tracking.reporter import Reporter


class Context:
    """What a ``module:function`` entrypoint receives as its only argument."""

    def __init__(
        self,
        *,
        params: Dict[str, Any],
        process_id: int = 0,
        num_processes: int = 1,
        mesh: Any = None,
        strategy: str = "ddp",
        strategy_options: Optional[Dict[str, Any]] = None,
        outputs_path: Optional[str] = None,
        checkpoints_path: Optional[str] = None,
        data_path: Optional[str] = None,
        runs_root: Optional[str] = None,
        reporter: Optional[Reporter] = None,
        seed: Optional[int] = None,
        run_uuid: Optional[str] = None,
    ) -> None:
        self.params = params
        self.process_id = process_id
        self.num_processes = num_processes
        # A Mesh, or a zero-arg thunk building one on first access: the
        # worker passes a thunk so non-jax entrypoints (metric probes,
        # shell services) never pay the jax import — the dominant cost of
        # a gang member's boot, and therefore of hpsearch wave throughput.
        self._mesh = mesh
        self.strategy = strategy
        self.strategy_options = strategy_options or {}
        self.outputs_path = Path(outputs_path) if outputs_path else None
        self.checkpoints_path = Path(checkpoints_path) if checkpoints_path else None
        #: The store layout's shared data/ dir (registered datasets).
        self.data_path = Path(data_path) if data_path else None
        #: The layout's runs/ dir (services resolving a target run's files).
        self.runs_root = Path(runs_root) if runs_root else None
        self.reporter = reporter
        self.seed = seed
        self.run_uuid = run_uuid

    # -- identity -------------------------------------------------------------
    @property
    def is_leader(self) -> bool:
        """Process 0 — the one that should write checkpoints/summaries."""
        return self.process_id == 0

    @property
    def mesh(self) -> Any:
        """The device mesh (built lazily on first access)."""
        if callable(self._mesh):
            self._mesh = self._mesh()
        return self._mesh

    @mesh.setter
    def mesh(self, value: Any) -> None:
        self._mesh = value

    # -- tracking -------------------------------------------------------------
    def log_metrics(self, step: Optional[int] = None, **values: Any) -> None:
        """Report metrics (leader-only by convention, like the reference's
        master-task metric reporting)."""
        if self.reporter is not None:
            self.reporter.metric(values, step=step)

    def log_text(self, line: str) -> None:
        if self.reporter is not None:
            self.reporter.log(line)

    def report_service(
        self, *, url: Optional[str] = None, query: Optional[str] = None
    ) -> None:
        """Advertise/refine this run's service URL (see Reporter.service)."""
        if self.reporter is not None:
            self.reporter.service(url=url, query=query)

    def get_param(self, name: str, default: Any = None) -> Any:
        return self.params.get(name, default)
