"""On-demand device profiling: the worker side of the run command bus.

The control plane drops ``<uuid>.json`` command files into this process's
mailbox (``commands/proc<N>/`` next to the report dir — the inverse of the
report channel); the :class:`Reporter` heartbeat thread polls the mailbox
via :meth:`CaptureAgent.poll` (idle cost: one listdir of an empty dir).
On a ``profile`` command the agent arms a windowed capture that the
workload's step loop drives through :meth:`CaptureAgent.on_step` — the
same hook trainers already give :class:`~polyaxon_tpu.tracking.profiling.
StepProfiler`, and the serving engine gives its decode iterations:

- an xplane trace (``jax.profiler.start_trace``/``stop_trace``) over the
  requested step window, viewable with xprof / tensorboard-profile;
- a device-memory snapshot (``jax.profiler.device_memory_profile``);
- the HLO text of any AOT-compiled executables the workload registered
  (PR 7's ``aot_compile`` products).

Everything lands under ``profiles/<capture_id>/proc<N>/`` in the run dir
(artifact-API visible, store-synced), and the lifecycle is reported as
typed ``capture``/``command`` lines the watcher folds into the registry's
``captures``/``commands`` tables.

Failure policy mirrors StepProfiler: profiling is diagnostics — any jax
profiler failure degrades the capture (xplane skipped, noted in attrs)
rather than crashing the workload; a capture that never sees a step
(idle serving engine, command-path worker) finalizes at its deadline with
whatever it could collect instead of hanging the command forever.

The command bus itself is generic: :meth:`CaptureAgent.register_handler`
lets future PRs route new command kinds (checkpoint-now, evict, restart)
through the same mailbox without touching delivery.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

_UNSET = object()

#: Capture window length when the command doesn't say (steps).
DEFAULT_NUM_STEPS = 5
#: Wall-clock budget for a capture whose step window never fills (an idle
#: serving engine, a cmd-path worker with no step loop): at the deadline
#: the poll thread finalizes with whatever was collected.
DEFAULT_DURATION_S = 30.0


class CaptureAgent:
    """Per-process command-mailbox poller + windowed profiling driver."""

    def __init__(self) -> None:
        self.reporter: Optional[Any] = None
        self.mailbox: Optional[Path] = None
        self.profiles_root: Optional[Path] = None
        self.process_id = 0
        self._lock = threading.RLock()
        self._executables: Dict[str, Any] = {}
        self._job: Optional[Dict[str, Any]] = None
        self._handlers: Dict[str, Callable[[Dict[str, Any]], None]] = {
            "profile": self._handle_profile,
        }
        self._closed = False

    def configure(
        self,
        *,
        reporter: Any = _UNSET,
        mailbox: Any = _UNSET,
        profiles_root: Any = _UNSET,
        process_id: Any = _UNSET,
    ) -> "CaptureAgent":
        with self._lock:
            if reporter is not _UNSET:
                self.reporter = reporter
            if mailbox is not _UNSET:
                self.mailbox = Path(mailbox) if mailbox is not None else None
            if profiles_root is not _UNSET:
                self.profiles_root = (
                    Path(profiles_root) if profiles_root is not None else None
                )
            if process_id is not _UNSET:
                self.process_id = int(process_id)
            self._closed = False
        return self

    # -- workload-facing registration -----------------------------------------
    def register_executable(self, name: str, compiled: Any) -> None:
        """Remember an AOT-compiled executable so captures can dump its HLO
        text.  Anything without ``as_text()`` is ignored at dump time."""
        if compiled is None:
            return
        with self._lock:
            self._executables[str(name)] = compiled

    def register_handler(
        self, kind: str, handler: Callable[[Dict[str, Any]], None]
    ) -> None:
        """Route a new command kind through the mailbox (bus extension
        point for checkpoint-now/evict/restart style commands)."""
        with self._lock:
            self._handlers[str(kind)] = handler

    # -- heartbeat-thread side ------------------------------------------------
    def poll(self) -> None:
        """Drain the mailbox and advance any deadline-stale capture.

        Rides the Reporter heartbeat thread (see ``add_beat_hook``): the
        idle cost is a single scandir of a usually-empty directory.
        """
        mailbox = self.mailbox
        if mailbox is None or self._closed:
            return
        try:
            entries = sorted(p for p in mailbox.iterdir() if p.suffix == ".json")
        except OSError:
            return
        for path in entries:
            try:
                cmd = json.loads(path.read_text())
            except (OSError, ValueError) as e:
                logger.warning("Unreadable command file %s: %s", path, e)
                try:
                    path.unlink()
                except OSError:
                    pass
                continue
            try:
                path.unlink()
            except OSError:
                # Another poll raced us to it; whoever unlinked dispatches.
                continue
            if isinstance(cmd, dict):
                self._dispatch(cmd)
            else:
                logger.warning("Non-object command file %s; dropped", path)
        self._reap_stale()

    def _dispatch(self, cmd: Dict[str, Any]) -> None:
        kind = str(cmd.get("kind") or "")
        uuid = str(cmd.get("uuid") or "")
        handler = self._handlers.get(kind)
        if handler is None:
            logger.warning("Unknown command kind %r (uuid %s); failing it", kind, uuid)
            self._command_event(uuid, "failed", message=f"unknown command kind {kind!r}")
            return
        self._command_event(uuid, "acked")
        try:
            handler(cmd)
        except Exception as e:
            logger.warning("Command %s (%s) handler failed", uuid, kind, exc_info=True)
            self._command_event(uuid, "failed", message=f"{type(e).__name__}: {e}")

    def _handle_profile(self, cmd: Dict[str, Any]) -> None:
        payload = cmd.get("payload") or {}
        capture_id = str(payload.get("capture_id") or cmd.get("uuid") or "capture")
        num_steps = int(payload.get("num_steps") or DEFAULT_NUM_STEPS)
        duration_s = float(payload.get("duration_s") or DEFAULT_DURATION_S)
        with self._lock:
            if self._job is not None:
                raise RuntimeError(
                    f"capture {self._job['capture_id']} already in flight"
                )
            if self.profiles_root is None:
                raise RuntimeError("capture agent has no profiles dir configured")
            out_dir = self.profiles_root / capture_id / f"proc{self.process_id}"
            out_dir.mkdir(parents=True, exist_ok=True)
            self._job = {
                "capture_id": capture_id,
                "command_uuid": str(cmd.get("uuid") or ""),
                "num_steps": max(1, num_steps),
                "deadline": time.time() + max(1.0, duration_s),
                "out_dir": out_dir,
                "state": "armed",  # armed → tracing → (finalized)
                "start_step": None,
                "steps_seen": 0,
                "started_at": None,
                "xplane": False,
                "notes": {},
            }
        self._emit_capture(
            capture_id,
            status="started",
            num_steps=num_steps,
            attrs={"duration_s": duration_s},
        )

    def _reap_stale(self) -> None:
        """Finalize a capture whose step window never filled by its
        deadline — a command must always resolve, even on a workload that
        stopped (or never started) stepping."""
        with self._lock:
            job = self._job
            if job is None or time.time() < job["deadline"]:
                return
            if job["state"] == "tracing":
                self._stop_trace(job)
                job["notes"]["window_truncated"] = True
            else:
                job["notes"]["no_step_window"] = True
            self._finalize(job)

    # -- workload-thread side -------------------------------------------------
    def on_step(self, step: int) -> None:
        """Call once per step/decode iteration; near-free while no capture
        is armed (one attribute read)."""
        if self._job is None:
            return
        with self._lock:
            job = self._job
            if job is None:
                return
            if job["state"] == "armed":
                job["state"] = "tracing"
                job["start_step"] = step
                job["started_at"] = time.time()
                try:
                    import jax

                    jax.profiler.start_trace(str(job["out_dir"] / "xplane"))
                    job["xplane"] = True
                except Exception as e:
                    # A launch-time StepProfiler window (or no profiler at
                    # all) owns the singleton trace — degrade, don't die.
                    logger.warning(
                        "Capture %s: start_trace failed (%s); continuing "
                        "without an xplane trace",
                        job["capture_id"],
                        e,
                    )
                    job["notes"]["xplane_error"] = f"{type(e).__name__}: {e}"
            job["steps_seen"] += 1
            if job["steps_seen"] >= job["num_steps"]:
                self._stop_trace(job)
                self._finalize(job)

    # -- finalization ---------------------------------------------------------
    def _stop_trace(self, job: Dict[str, Any]) -> None:
        if not job.get("xplane"):
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            logger.warning(
                "Capture %s: stop_trace failed: %s", job["capture_id"], e
            )
            job["xplane"] = False
            job["notes"]["xplane_error"] = f"{type(e).__name__}: {e}"

    def _finalize(self, job: Dict[str, Any]) -> None:
        """Write memory/HLO/manifest artifacts and report the outcome.
        Best-effort per section — one failed collector costs its artifact,
        not the capture."""
        out_dir: Path = job["out_dir"]
        artifacts: List[str] = []

        def _rel(p: Path) -> str:
            # Keys are run-root relative (profiles/<cid>/proc<N>/...), the
            # shape the artifacts API serves.
            root = self.profiles_root.parent if self.profiles_root else out_dir
            try:
                return p.relative_to(root).as_posix()
            except ValueError:
                return p.as_posix()

        if job.get("xplane"):
            xdir = out_dir / "xplane"
            artifacts.extend(
                _rel(p) for p in sorted(xdir.rglob("*")) if p.is_file()
            )
        try:
            import jax

            prof = jax.profiler.device_memory_profile()
            if prof:
                mem = out_dir / "memory.prof"
                mem.write_bytes(prof)
                artifacts.append(_rel(mem))
        except Exception as e:
            job["notes"]["memory_error"] = f"{type(e).__name__}: {e}"
        hlo_texts = []
        with self._lock:
            executables = dict(self._executables)
        for name, compiled in executables.items():
            try:
                text = compiled.as_text()
            except Exception:
                continue
            if text:
                hlo_texts.append(f"// executable: {name}\n{text}")
        if hlo_texts:
            try:
                hlo = out_dir / "hlo.txt"
                hlo.write_text("\n\n".join(hlo_texts))
                artifacts.append(_rel(hlo))
            except OSError as e:
                job["notes"]["hlo_error"] = f"{type(e).__name__}: {e}"
        finished_at = time.time()
        record = {
            "capture_id": job["capture_id"],
            "command_uuid": job["command_uuid"],
            "status": "complete",
            "start_step": job["start_step"],
            "num_steps": job["steps_seen"] or None,
            "started_at": job["started_at"],
            "finished_at": finished_at,
            "artifacts": artifacts,
            "attrs": {"xplane": bool(job.get("xplane")), **job["notes"]},
        }
        try:
            manifest = out_dir / "manifest.json"
            manifest.write_text(json.dumps(record, indent=2, default=str))
            artifacts.append(_rel(manifest))
        except OSError as e:
            job["notes"]["manifest_error"] = f"{type(e).__name__}: {e}"
        self._job = None
        self._emit_capture_record(record)
        self._command_event(job["command_uuid"], "complete")

    def _abort(self, message: str) -> None:
        with self._lock:
            job = self._job
            if job is None:
                return
            self._stop_trace(job)
            self._job = None
        self._emit_capture(
            job["capture_id"],
            status="failed",
            message=message,
            attrs=job["notes"],
        )
        self._command_event(job["command_uuid"], "failed", message=message)

    def close(self) -> None:
        """Resolve any in-flight capture before the worker exits — a
        half-done capture reports failed, never silence."""
        self._closed = True
        self._abort("worker exited mid-capture")

    # -- reporting ------------------------------------------------------------
    def _emit_capture(self, capture_id: str, **fields: Any) -> None:
        record = {"capture_id": capture_id, **fields}
        self._emit_capture_record(record)

    def _emit_capture_record(self, record: Dict[str, Any]) -> None:
        if self.reporter is None:
            return
        try:
            self.reporter.capture(record)
        except Exception:
            logger.warning("Failed to report capture record", exc_info=True)

    def command_event(
        self, uuid: str, state: str, message: Optional[str] = None, **attrs: Any
    ) -> None:
        """Report a per-process command state — the public surface for
        registered handlers that resolve a command later, off the dispatch
        thread (checkpoint-now completes from the train loop this way).
        Extra kwargs ride the report line into the command's ack attrs."""
        self._command_event(uuid, state, message=message, **attrs)

    def _command_event(
        self, uuid: str, state: str, message: Optional[str] = None, **attrs: Any
    ) -> None:
        if self.reporter is None or not uuid:
            return
        try:
            self.reporter.command_event(uuid, state, message=message, **attrs)
        except Exception:
            logger.warning("Failed to report command state", exc_info=True)


_agent = CaptureAgent()


def get_capture_agent() -> CaptureAgent:
    return _agent


def configure(**kwargs: Any) -> CaptureAgent:
    return _agent.configure(**kwargs)
