from polyaxon_tpu.tracking.context import Context
from polyaxon_tpu.tracking.reporter import Reporter
from polyaxon_tpu.tracking.trace import Tracer, chrome_trace, get_tracer

__all__ = ["Context", "Reporter", "Tracer", "chrome_trace", "get_tracer"]
