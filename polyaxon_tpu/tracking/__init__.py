from polyaxon_tpu.tracking.context import Context
from polyaxon_tpu.tracking.reporter import Reporter

__all__ = ["Context", "Reporter"]
