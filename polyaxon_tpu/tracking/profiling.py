"""Per-step tracing/profiling hooks.

The reference has no tracer at all (SURVEY §5: observability = StatsD
counters + Sentry) — real per-step device profiling is a TPU-first
addition: a windowed ``jax.profiler`` trace (xplane) written into the
run's managed outputs dir, viewable with xprof/tensorboard, plus
annotation helpers for named trace spans.
"""

from __future__ import annotations

import contextlib
import logging
from pathlib import Path
from typing import Optional, Union

logger = logging.getLogger(__name__)


class StepProfiler:
    """Capture a jax.profiler trace for steps [start, start+num_steps).

    Failure policy: profiling is diagnostics, never the workload — any
    ``start_trace``/``stop_trace`` failure (profiler unavailable, trace
    dir unwritable, another trace already active) warns and DISABLES the
    profiler instead of crashing the train loop.  ``close()`` is
    idempotent.
    """

    def __init__(
        self,
        outputs_dir: Union[str, Path],
        start_step: int = -1,
        num_steps: int = 0,
    ) -> None:
        self.trace_dir = str(Path(outputs_dir) / "profile")
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False
        self._broken = False

    @property
    def enabled(self) -> bool:
        return self.num_steps > 0 and self.start_step >= 0 and not self._broken

    def _disable(self, op: str, exc: Exception) -> None:
        logger.warning(
            "StepProfiler %s failed (%s: %s); disabling profiling for this run",
            op,
            type(exc).__name__,
            exc,
        )
        self._broken = True
        self._active = False

    def on_step(self, step: int) -> None:
        """Call once per train step (before dispatch)."""
        if not self.enabled:
            return
        if not self._active and step == self.start_step:
            try:
                import jax

                jax.profiler.start_trace(self.trace_dir)
                self._active = True
            except Exception as e:
                self._disable("start_trace", e)
        elif self._active and step >= self.start_step + self.num_steps:
            try:
                import jax

                jax.profiler.stop_trace()
                self._active = False
            except Exception as e:
                self._disable("stop_trace", e)

    def close(self) -> None:
        if self._active:
            self._active = False
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                self._disable("stop_trace", e)


def annotate(name: str):
    """Named trace span context manager (no-op cost when not tracing;
    no-op entirely when jax.profiler is unavailable)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class StepClock:
    """Per-step wall/section accounting for the train hot loop.

    Cheap enough to run every step (two ``perf_counter`` calls + dict
    adds): ``tick()`` marks a step boundary and accumulates
    ``step_wall_s``; ``add(name, seconds)`` folds in externally measured
    sections (``data_wait_s`` from the pipeline, ``ckpt_block_s`` from the
    checkpoint manager).  :meth:`summary` reports per-step MEANS — the
    numbers the tracker surfaces so "where did the step go" is answerable
    without a trace: a healthy overlapped loop shows ``data_wait_s`` and
    ``ckpt_block_s`` ≪ ``step_wall_s``.
    """

    def __init__(self) -> None:
        from time import perf_counter

        self._clock = perf_counter
        self.steps = 0
        self.totals: dict = {"step_wall_s": 0.0}
        self._last: Optional[float] = None

    def start(self) -> None:
        """Arm at loop entry (the first tick measures the first step)."""
        self._last = self._clock()

    def tick(self) -> Optional[float]:
        """Call once at the end of every step; returns this step's wall
        seconds (None on the unarmed first call) so callers can feed a
        per-step histogram without a second clock read."""
        now = self._clock()
        dt: Optional[float] = None
        if self._last is not None:
            dt = now - self._last
            self.totals["step_wall_s"] += dt
            self.steps += 1
        self._last = now
        return dt

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + seconds

    def summary(self) -> dict:
        """Per-step means, keyed by section name (empty if no steps ran)."""
        if not self.steps:
            return {}
        return {k: v / self.steps for k, v in self.totals.items()}
