"""Per-step tracing/profiling hooks.

The reference has no tracer at all (SURVEY §5: observability = StatsD
counters + Sentry) — real per-step device profiling is a TPU-first
addition: a windowed ``jax.profiler`` trace (xplane) written into the
run's managed outputs dir, viewable with xprof/tensorboard, plus
annotation helpers for named trace spans.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union


class StepProfiler:
    """Capture a jax.profiler trace for steps [start, start+num_steps)."""

    def __init__(
        self,
        outputs_dir: Union[str, Path],
        start_step: int = -1,
        num_steps: int = 0,
    ) -> None:
        self.trace_dir = str(Path(outputs_dir) / "profile")
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.num_steps > 0 and self.start_step >= 0

    def on_step(self, step: int) -> None:
        """Call once per train step (before dispatch)."""
        if not self.enabled:
            return
        import jax

        if not self._active and step == self.start_step:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and step >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


def annotate(name: str):
    """Named trace span context manager (no-op cost when not tracing)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class StepClock:
    """Per-step wall/section accounting for the train hot loop.

    Cheap enough to run every step (two ``perf_counter`` calls + dict
    adds): ``tick()`` marks a step boundary and accumulates
    ``step_wall_s``; ``add(name, seconds)`` folds in externally measured
    sections (``data_wait_s`` from the pipeline, ``ckpt_block_s`` from the
    checkpoint manager).  :meth:`summary` reports per-step MEANS — the
    numbers the tracker surfaces so "where did the step go" is answerable
    without a trace: a healthy overlapped loop shows ``data_wait_s`` and
    ``ckpt_block_s`` ≪ ``step_wall_s``.
    """

    def __init__(self) -> None:
        from time import perf_counter

        self._clock = perf_counter
        self.steps = 0
        self.totals: dict = {"step_wall_s": 0.0}
        self._last: Optional[float] = None

    def start(self) -> None:
        """Arm at loop entry (the first tick measures the first step)."""
        self._last = self._clock()

    def tick(self) -> Optional[float]:
        """Call once at the end of every step; returns this step's wall
        seconds (None on the unarmed first call) so callers can feed a
        per-step histogram without a second clock read."""
        now = self._clock()
        dt: Optional[float] = None
        if self._last is not None:
            dt = now - self._last
            self.totals["step_wall_s"] += dt
            self.steps += 1
        self._last = now
        return dt

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + seconds

    def summary(self) -> dict:
        """Per-step means, keyed by section name (empty if no steps ran)."""
        if not self.steps:
            return {}
        return {k: v / self.steps for k, v in self.totals.items()}
