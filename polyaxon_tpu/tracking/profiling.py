"""Per-step tracing/profiling hooks.

The reference has no tracer at all (SURVEY §5: observability = StatsD
counters + Sentry) — real per-step device profiling is a TPU-first
addition: a windowed ``jax.profiler`` trace (xplane) written into the
run's managed outputs dir, viewable with xprof/tensorboard, plus
annotation helpers for named trace spans.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union


class StepProfiler:
    """Capture a jax.profiler trace for steps [start, start+num_steps)."""

    def __init__(
        self,
        outputs_dir: Union[str, Path],
        start_step: int = -1,
        num_steps: int = 0,
    ) -> None:
        self.trace_dir = str(Path(outputs_dir) / "profile")
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.num_steps > 0 and self.start_step >= 0

    def on_step(self, step: int) -> None:
        """Call once per train step (before dispatch)."""
        if not self.enabled:
            return
        import jax

        if not self._active and step == self.start_step:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and step >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


def annotate(name: str):
    """Named trace span context manager (no-op cost when not tracing)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
