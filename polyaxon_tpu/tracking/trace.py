"""Lightweight structured span tracing across the gang.

The reference stack stops at StatsD counters + Sentry (SURVEY §5); it has
no way to answer "where did this step/request/trial spend its time" across
the control plane, the gang workers, and the serving engine.  This module
is the worker-side half of that answer:

- :class:`Tracer` hands out ``span(name, **attrs)`` context managers that
  record wall-clock start (``time.time()``, so spans from different hosts
  line up on one timeline) and a ``perf_counter`` duration, plus
  trace/span/parent ids maintained per thread for nesting.
- Finished spans land in a thread-safe ring buffer and, when a ``sink`` is
  configured (the worker wires ``Reporter.span``), ship through the
  existing report channel as a typed ``span`` event.  ``GangWatcher``
  ingests those into the registry, and the control plane exports the
  cross-process timeline as Chrome-trace JSON (:func:`chrome_trace`,
  served at ``GET /api/v1/runs/<id>/timeline``).
- Sampling is decided *before* any ids or timestamps are taken: a
  sampled-out ``span()`` call returns a shared no-op context manager, so
  hot-path call sites (per step / per decode tick, gated on
  ``tracer.hot_sample``) cost about as much as a ``perf_counter`` call.

Process-wide singleton: library code calls :func:`get_tracer` and never
configures it; the worker entrypoint calls :func:`configure` once with the
report sink, its process id, and the run uuid.  Control-plane spans stay
buffer-only (no sink) unless something attaches one.

Request-scoped *distributed* tracing rides on the same records: a
W3C-traceparent-style :class:`TraceContext` (``inject`` / ``extract``
header helpers) carries one trace id across the serving hops (router →
replica lm_server → engine), and spans created with explicit
``trace_id`` / ``parent_id`` overrides (or recorded after the fact via
:meth:`Tracer.record_span`) stitch the per-process records into one
cross-host timeline.  ``chrome_trace`` keys its rows by *(process
label, pid)* so router + replica spans — which all default to
``process_id=0`` — land on distinct named tracks.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional

from polyaxon_tpu.conf.knobs import knob_float

__all__ = [
    "Tracer",
    "get_tracer",
    "configure",
    "chrome_trace",
    "TraceContext",
    "TRACEPARENT_HEADER",
    "new_trace_id",
    "inject",
    "extract",
]

_UNSET = object()

#: The propagation header, lowercase per W3C Trace Context.
TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    """A fresh 32-hex-char trace id (W3C trace-id width)."""
    return os.urandom(16).hex()


class TraceContext:
    """Propagated trace state: one trace id + the remote parent span.

    ``span_id`` is the *caller's* span — the hop that injected the
    header — so spans the receiving process creates parent to it and
    the merged timeline nests correctly across hosts.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(
        self, trace_id: str, span_id: str = "", sampled: bool = True
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def header(self) -> str:
        """Serialize as a ``version-traceid-spanid-flags`` header value.

        The span-id field is 16 hex chars per the W3C layout; internal
        span ids (``<label>.<n>``) don't fit that alphabet, so they are
        carried verbatim — both ends of every hop are this module.
        """
        return "00-%s-%s-%s" % (
            self.trace_id,
            self.span_id or "0" * 16,
            "01" if self.sampled else "00",
        )

    def child(self, span_id: str) -> "TraceContext":
        """The context to inject on an outbound hop parented to
        ``span_id`` (a span of the current process)."""
        return TraceContext(self.trace_id, span_id, self.sampled)


def inject(ctx: Optional[TraceContext], headers: Dict[str, str]) -> Dict[str, str]:
    """Write ``ctx`` into an outbound header dict (no-op when None)."""
    if ctx is not None:
        headers[TRACEPARENT_HEADER] = ctx.header()
    return headers


def extract(headers: Optional[Mapping[str, Any]]) -> Optional[TraceContext]:
    """Parse a traceparent header from ``headers`` (case-insensitive).

    Malformed or missing headers return None — the caller degrades to a
    fresh trace; propagation must never turn into a 500.
    """
    if headers is None:
        return None
    try:
        raw = headers.get(TRACEPARENT_HEADER) or headers.get(
            TRACEPARENT_HEADER.title()
        )
    except Exception:
        return None
    if not raw or not isinstance(raw, str):
        return None
    parts = raw.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or not trace_id or trace_id.strip("0") == "":
        return None
    if len(trace_id) != 32:
        return None
    try:
        int(trace_id, 16)
        int(flags, 16)
    except ValueError:
        return None
    sampled = False
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        pass
    if span_id.strip("0") == "":
        span_id = ""
    return TraceContext(trace_id, span_id, sampled)


class _NoopSpan:
    """Shared zero-state stand-in yielded when a span is sampled out."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    """A live (sampled-in) span; created by :meth:`Tracer.span`."""

    __slots__ = (
        "_tracer",
        "name",
        "attrs",
        "span_id",
        "parent_id",
        "_trace_id",
        "_explicit_parent",
        "_t0",
        "_p0",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        attrs: Dict[str, Any],
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = ""
        self.parent_id: Optional[str] = parent_id
        self._trace_id = trace_id
        self._explicit_parent = parent_id is not None
        self._t0 = 0.0
        self._p0 = 0.0

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered while the span body runs."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        tracer = self._tracer
        stack = tracer._stack()
        if not self._explicit_parent:
            self.parent_id = stack[-1] if stack else None
        self.span_id = tracer.next_span_id()
        stack.append(self.span_id)
        self._t0 = time.time()
        self._p0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        duration = time.perf_counter() - self._p0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = getattr(exc_type, "__name__", str(exc_type))
        self._tracer.record_span(
            self.name,
            start=self._t0,
            duration=duration,
            trace_id=(
                self._trace_id
                if self._trace_id is not None
                else self._tracer.trace_id
            ),
            span_id=self.span_id,
            parent_id=self.parent_id,
            **self.attrs,
        )
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    ``sample`` gates ordinary spans, ``hot_sample`` is the conventional
    rate call sites use for per-step/per-token spans (pass it explicitly:
    ``tracer.span("train.step", sample=tracer.hot_sample)``).  Both are
    env-tunable so a run can be re-launched fully traced without a code
    change.
    """

    def __init__(
        self,
        *,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        sample: float = 1.0,
        hot_sample: float = 0.05,
        buffer: int = 2048,
        process_id: int = 0,
        process: str = "",
        trace_id: Optional[str] = None,
    ) -> None:
        self.sink = sink
        self.sample = sample
        self.hot_sample = hot_sample
        self.process_id = process_id
        self.process = process
        self.trace_id = trace_id
        self._buffer: deque = deque(maxlen=buffer)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        *,
        sink: Any = _UNSET,
        sample: Any = _UNSET,
        hot_sample: Any = _UNSET,
        process_id: Any = _UNSET,
        process: Any = _UNSET,
        trace_id: Any = _UNSET,
    ) -> "Tracer":
        """Update settings in place (unset arguments keep current values)."""
        if sink is not _UNSET:
            self.sink = sink
        if sample is not _UNSET:
            self.sample = float(sample)
        if hot_sample is not _UNSET:
            self.hot_sample = float(hot_sample)
        if process_id is not _UNSET:
            self.process_id = int(process_id)
        if process is not _UNSET:
            self.process = str(process)
        if trace_id is not _UNSET:
            self.trace_id = trace_id
        return self

    # -- recording ----------------------------------------------------------

    def span(
        self,
        name: str,
        sample: Optional[float] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ):
        """Context manager timing ``name``; sampled-out calls are ~free.

        ``trace_id`` / ``parent_id`` override the process trace id and
        the thread-local parent stack — request-scoped spans pass the
        propagated :class:`TraceContext` ids so phases executed on a
        shared scheduler thread still nest under their own request.
        Sampling uses the module-level ``random.random()`` (its own lock
        via the shared Random's C implementation) — a per-instance RNG
        here would be raced by concurrent HTTP handler threads.
        """
        rate = self.sample if sample is None else sample
        if rate < 1.0 and (rate <= 0.0 or random.random() >= rate):
            return _NOOP
        return _Span(self, name, attrs, trace_id=trace_id, parent_id=parent_id)

    def next_span_id(self) -> str:
        """Allocate a span id unique within (and, when a process label is
        set, across) processes: ``[label.]pid.counter``."""
        n = next(self._ids)
        if self.process:
            return "%s.%d.%x" % (self.process, self.process_id, n)
        return "%d.%x" % (self.process_id, n)

    def record_span(
        self,
        name: str,
        *,
        start: float,
        duration: float,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ) -> Dict[str, Any]:
        """Record a completed span directly (no context manager).

        The engine uses this to emit request phases measured by its own
        accounting (queue wait, park intervals, the request root) whose
        start/end don't bracket a ``with`` block.
        """
        record: Dict[str, Any] = {
            "name": name,
            "trace_id": trace_id if trace_id is not None else self.trace_id,
            "span_id": span_id if span_id is not None else self.next_span_id(),
            "parent_id": parent_id,
            "start": start,
            "duration": duration,
            "process_id": self.process_id,
            "thread": threading.current_thread().name,
        }
        if self.process:
            record["process"] = self.process
        process = attrs.pop("process", None)
        if process:
            record["process"] = str(process)
        if attrs:
            record["attrs"] = attrs
        self._record(record)
        return record

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._buffer.append(record)
        sink = self.sink
        if sink is not None:
            try:
                sink(record)
            except Exception:
                pass  # a broken sink must never take down the traced code

    def spans(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring buffer, oldest first."""
        with self._lock:
            return list(self._buffer)

    def clear(self) -> None:
        with self._lock:
            self._buffer.clear()


_tracer = Tracer(
    sample=knob_float("POLYAXON_TPU_TRACE_SAMPLE"),
    hot_sample=knob_float("POLYAXON_TPU_TRACE_HOT_SAMPLE"),
)


def get_tracer() -> Tracer:
    """The process-wide tracer (unconfigured: buffer-only, no sink)."""
    return _tracer


def configure(**kwargs: Any) -> Tracer:
    """Configure the process-wide tracer (see :meth:`Tracer.configure`)."""
    return _tracer.configure(**kwargs)


def chrome_trace(spans: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Render span records as Chrome-trace / Perfetto JSON.

    Each span becomes a complete ("ph": "X") event; timestamps are the
    original wall-clock epoch in microseconds, so spans reported by
    different gang processes land on one shared timeline.  Process rows
    are keyed by *(process label, process_id)* — serving processes
    (router, every replica) all default to ``process_id=0``, so the
    label is what keeps a merged fleet trace on distinct tracks — with
    process_name/thread_name metadata so the viewer labels each one.
    Unlabeled gang spans keep their process_id as the pid, preserving
    the existing run-timeline export.
    """
    events: List[Dict[str, Any]] = []
    tids: Dict[Any, int] = {}
    per_pid: Dict[Any, int] = {}
    pids: Dict[Any, int] = {}
    for span in spans:
        raw_pid = int(span.get("process_id") or 0)
        label = str(span.get("process") or "")
        pkey = (label, raw_pid)
        pid = pids.get(pkey)
        if pid is None:
            # Labeled processes get synthetic pids above the unlabeled
            # range so "router" and gang process 0 never share a row.
            pid = raw_pid if not label else 10_000 + len(pids)
            while label and pid in pids.values():
                pid += 1
            pids[pkey] = pid
            events.append(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": pid,
                    "tid": 0,
                    "args": {
                        "name": label or ("process %d" % raw_pid),
                    },
                }
            )
        thread = str(span.get("thread") or "main")
        key = (pkey, thread)
        tid = tids.get(key)
        if tid is None:
            tid = per_pid.get(pkey, 0) + 1
            per_pid[pkey] = tid
            tids[key] = tid
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": thread},
                }
            )
        args: Dict[str, Any] = {}
        attrs = span.get("attrs")
        if isinstance(attrs, dict):
            args.update(attrs)
        for field in ("trace_id", "span_id", "parent_id"):
            value = span.get(field)
            if value:
                args[field] = value
        event: Dict[str, Any] = {
            "name": str(span.get("name") or "span"),
            "ph": "X",
            "cat": "span",
            "pid": pid,
            "tid": tid,
            "ts": float(span.get("start") or 0.0) * 1e6,
            "dur": float(span.get("duration") or 0.0) * 1e6,
        }
        if args:
            event["args"] = args
        events.append(event)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
