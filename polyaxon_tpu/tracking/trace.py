"""Lightweight structured span tracing across the gang.

The reference stack stops at StatsD counters + Sentry (SURVEY §5); it has
no way to answer "where did this step/request/trial spend its time" across
the control plane, the gang workers, and the serving engine.  This module
is the worker-side half of that answer:

- :class:`Tracer` hands out ``span(name, **attrs)`` context managers that
  record wall-clock start (``time.time()``, so spans from different hosts
  line up on one timeline) and a ``perf_counter`` duration, plus
  trace/span/parent ids maintained per thread for nesting.
- Finished spans land in a thread-safe ring buffer and, when a ``sink`` is
  configured (the worker wires ``Reporter.span``), ship through the
  existing report channel as a typed ``span`` event.  ``GangWatcher``
  ingests those into the registry, and the control plane exports the
  cross-process timeline as Chrome-trace JSON (:func:`chrome_trace`,
  served at ``GET /api/v1/runs/<id>/timeline``).
- Sampling is decided *before* any ids or timestamps are taken: a
  sampled-out ``span()`` call returns a shared no-op context manager, so
  hot-path call sites (per step / per decode tick, gated on
  ``tracer.hot_sample``) cost about as much as a ``perf_counter`` call.

Process-wide singleton: library code calls :func:`get_tracer` and never
configures it; the worker entrypoint calls :func:`configure` once with the
report sink, its process id, and the run uuid.  Control-plane spans stay
buffer-only (no sink) unless something attaches one.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional

from polyaxon_tpu.conf.knobs import knob_float

__all__ = ["Tracer", "get_tracer", "configure", "chrome_trace"]

_UNSET = object()


class _NoopSpan:
    """Shared zero-state stand-in yielded when a span is sampled out."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    """A live (sampled-in) span; created by :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent_id", "_t0", "_p0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = ""
        self.parent_id: Optional[str] = None
        self._t0 = 0.0
        self._p0 = 0.0

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered while the span body runs."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        tracer = self._tracer
        stack = tracer._stack()
        self.parent_id = stack[-1] if stack else None
        self.span_id = "%d.%x" % (tracer.process_id, next(tracer._ids))
        stack.append(self.span_id)
        self._t0 = time.time()
        self._p0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        duration = time.perf_counter() - self._p0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        record: Dict[str, Any] = {
            "name": self.name,
            "trace_id": self._tracer.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self._t0,
            "duration": duration,
            "process_id": self._tracer.process_id,
            "thread": threading.current_thread().name,
        }
        if exc_type is not None:
            self.attrs["error"] = getattr(exc_type, "__name__", str(exc_type))
        if self.attrs:
            record["attrs"] = self.attrs
        self._tracer._record(record)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    ``sample`` gates ordinary spans, ``hot_sample`` is the conventional
    rate call sites use for per-step/per-token spans (pass it explicitly:
    ``tracer.span("train:step", sample=tracer.hot_sample)``).  Both are
    env-tunable so a run can be re-launched fully traced without a code
    change.
    """

    def __init__(
        self,
        *,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        sample: float = 1.0,
        hot_sample: float = 0.05,
        buffer: int = 2048,
        process_id: int = 0,
        trace_id: Optional[str] = None,
    ) -> None:
        self.sink = sink
        self.sample = sample
        self.hot_sample = hot_sample
        self.process_id = process_id
        self.trace_id = trace_id
        self._buffer: deque = deque(maxlen=buffer)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._rng = random.Random()

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        *,
        sink: Any = _UNSET,
        sample: Any = _UNSET,
        hot_sample: Any = _UNSET,
        process_id: Any = _UNSET,
        trace_id: Any = _UNSET,
    ) -> "Tracer":
        """Update settings in place (unset arguments keep current values)."""
        if sink is not _UNSET:
            self.sink = sink
        if sample is not _UNSET:
            self.sample = float(sample)
        if hot_sample is not _UNSET:
            self.hot_sample = float(hot_sample)
        if process_id is not _UNSET:
            self.process_id = int(process_id)
        if trace_id is not _UNSET:
            self.trace_id = trace_id
        return self

    # -- recording ----------------------------------------------------------

    def span(self, name: str, sample: Optional[float] = None, **attrs: Any):
        """Context manager timing ``name``; sampled-out calls are ~free."""
        rate = self.sample if sample is None else sample
        if rate < 1.0 and (rate <= 0.0 or self._rng.random() >= rate):
            return _NOOP
        return _Span(self, name, attrs)

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._buffer.append(record)
        sink = self.sink
        if sink is not None:
            try:
                sink(record)
            except Exception:
                pass  # a broken sink must never take down the traced code

    def spans(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring buffer, oldest first."""
        with self._lock:
            return list(self._buffer)

    def clear(self) -> None:
        with self._lock:
            self._buffer.clear()


_tracer = Tracer(
    sample=knob_float("POLYAXON_TPU_TRACE_SAMPLE"),
    hot_sample=knob_float("POLYAXON_TPU_TRACE_HOT_SAMPLE"),
)


def get_tracer() -> Tracer:
    """The process-wide tracer (unconfigured: buffer-only, no sink)."""
    return _tracer


def configure(**kwargs: Any) -> Tracer:
    """Configure the process-wide tracer (see :meth:`Tracer.configure`)."""
    return _tracer.configure(**kwargs)


def chrome_trace(spans: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Render span records as Chrome-trace / Perfetto JSON.

    Each span becomes a complete ("ph": "X") event; timestamps are the
    original wall-clock epoch in microseconds, so spans reported by
    different gang processes land on one shared timeline.  Rows are keyed
    (pid=process_id, tid=per-process thread index) with thread_name
    metadata so the viewer labels each track.
    """
    events: List[Dict[str, Any]] = []
    tids: Dict[Any, int] = {}
    per_pid: Dict[int, int] = {}
    for span in spans:
        pid = int(span.get("process_id") or 0)
        thread = str(span.get("thread") or "main")
        key = (pid, thread)
        tid = tids.get(key)
        if tid is None:
            tid = per_pid.get(pid, 0) + 1
            per_pid[pid] = tid
            tids[key] = tid
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": thread},
                }
            )
        args: Dict[str, Any] = {}
        attrs = span.get("attrs")
        if isinstance(attrs, dict):
            args.update(attrs)
        for field in ("trace_id", "span_id", "parent_id"):
            value = span.get(field)
            if value:
                args[field] = value
        event: Dict[str, Any] = {
            "name": str(span.get("name") or "span"),
            "ph": "X",
            "cat": "span",
            "pid": pid,
            "tid": tid,
            "ts": float(span.get("start") or 0.0) * 1e6,
            "dur": float(span.get("duration") or 0.0) * 1e6,
        }
        if args:
            event["args"] = args
        events.append(event)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
