"""Stall watchdog + crash-forensics flight recorder (worker side).

The reference platform's observation stack only sees *terminal* outcomes —
a pod that dies is reconciled, a pod that is alive but silently stuck (a
hung collective, a wedged input pipeline, a straggling host) is invisible
until the heartbeat TTL expires hours later.  This module is the worker
half of the anomaly-detection layer:

- :class:`Progress` — a process-wide beacon the hot loops feed.  Trainers
  beat once per optimizer step, the serving engine once per decode tick.
  A beat is a lock + a few attribute writes: cheap enough for any loop
  that is already paying a ``perf_counter`` for its step clock.
- :class:`FlightRecorder` — a daemon watchdog thread that (a) relays the
  beacon upstream as typed ``progress`` report lines (step / epoch /
  throughput, throttled — the control plane's straggler detector runs on
  these), and (b) dumps a forensic snapshot when no beat lands within an
  *adaptive* deadline: k× the rolling step-time median, clamped between a
  floor and a ceiling, so a 50ms-step CPU probe and a 30s-step LLM run
  get proportionate patience from the same knobs.

The forensic snapshot — every live thread's stack from
``sys._current_frames()``, the tracer's span ring buffer, accelerator
memory stats, the tail of this process's own report file — is written to
``reports/flightrec-<proc>-<n>.json`` next to the report channel, and a
typed ``anomaly`` line points the control plane at it.  The same dump
fires from the worker entrypoint's crash path, so every FAILED run leaves
a postmortem.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from pathlib import Path
from typing import Any, Dict, Optional

from polyaxon_tpu.conf.knobs import knob_float


class Progress:
    """Shared progress beacon: hot loops call :meth:`beat`, nothing else.

    Thread-safe; the watchdog (and tests) read a consistent copy via
    :meth:`snapshot`.  The deadline math runs on ``perf_counter`` so wall
    clock adjustments can never fake a stall; wall time is kept alongside
    for the upstream ``progress`` lines.
    """

    def __init__(self, window: int = 64) -> None:
        self._lock = threading.Lock()
        self._dts: deque = deque(maxlen=window)
        self._beats = 0
        self._step: Optional[int] = None
        self._epoch: Optional[int] = None
        self._last_mono: Optional[float] = None
        self._last_wall: Optional[float] = None

    def beat(
        self, step: Optional[int] = None, *, epoch: Optional[int] = None
    ) -> None:
        """Record one unit of forward progress (a train step, a decode tick)."""
        mono = time.perf_counter()
        with self._lock:
            if self._last_mono is not None:
                self._dts.append(mono - self._last_mono)
            self._beats += 1
            self._last_mono = mono
            self._last_wall = time.time()
            if step is not None:
                self._step = step
            if epoch is not None:
                self._epoch = epoch

    def reset(self) -> None:
        """Disarm the beacon (between entrypoints / in tests)."""
        with self._lock:
            self._dts.clear()
            self._beats = 0
            self._step = self._epoch = None
            self._last_mono = self._last_wall = None

    def snapshot(self) -> Dict[str, Any]:
        """Consistent view: armed/step/epoch, beat age, rolling median dt."""
        with self._lock:
            median_dt = statistics.median(self._dts) if self._dts else None
            age = (
                time.perf_counter() - self._last_mono
                if self._last_mono is not None
                else None
            )
            return {
                "armed": self._beats > 0,
                "beats": self._beats,
                "step": self._step,
                "epoch": self._epoch,
                "age_s": age,
                "last_beat_at": self._last_wall,
                "median_dt_s": median_dt,
                "throughput": (1.0 / median_dt) if median_dt else None,
            }


#: Process-wide beacon, mirroring the tracer singleton: hot loops reach it
#: via :func:`get_progress` with no plumbing through Context/engine APIs.
_progress = Progress()


def get_progress() -> Progress:
    return _progress


def thread_stacks() -> Dict[str, Any]:
    """Every live thread's current stack, keyed ``<name>:<ident>``.

    ``sys._current_frames()`` is a point-in-time copy — no tracing overhead
    until the moment of the dump, which is exactly the flight-recorder
    trade: free when healthy, complete when stuck.
    """
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        f"{names.get(ident, 'unknown')}:{ident}": traceback.format_stack(frame)
        for ident, frame in sys._current_frames().items()
    }


def dump_forensics(
    out_dir: Path,
    process_id: int,
    seq: int,
    *,
    kind: str,
    message: Optional[str] = None,
    progress: Optional[Dict[str, Any]] = None,
    report_path: Optional[Path] = None,
    exc: Optional[BaseException] = None,
    span_tail: int = 200,
    report_tail_lines: int = 50,
) -> Optional[Path]:
    """Write ``flightrec-<proc>-<seq>.json`` and return its path.

    Every ingredient is gathered best-effort behind its own guard: a
    postmortem with a missing section beats no postmortem — this runs on
    the crash path and inside the watchdog thread, where a second failure
    must never mask the first.
    """
    snapshot: Dict[str, Any] = {
        "kind": kind,
        "ts": time.time(),
        "process_id": process_id,
        "message": message,
        "progress": progress,
    }
    try:
        snapshot["threads"] = thread_stacks()
    except Exception as e:
        snapshot["threads"] = {"error": repr(e)}
    try:
        from polyaxon_tpu.tracking.trace import get_tracer

        snapshot["spans"] = get_tracer().spans()[-span_tail:]
    except Exception as e:
        snapshot["spans"] = [{"error": repr(e)}]
    try:
        from polyaxon_tpu.monitor.resources import sample_devices

        snapshot["devices"] = sample_devices()
    except Exception as e:
        snapshot["devices"] = {"error": repr(e)}
    if exc is not None:
        snapshot["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": traceback.format_exception(type(exc), exc, exc.__traceback__),
        }
    if report_path is not None:
        try:
            with open(report_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - 64 * 1024))
                tail = fh.read().decode("utf-8", errors="replace")
            snapshot["report_tail"] = tail.splitlines()[-report_tail_lines:]
        except Exception as e:
            snapshot["report_tail"] = [f"error: {e!r}"]
    try:
        out_dir = Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / f"flightrec-{process_id}-{seq}.json"
        path.write_text(json.dumps(snapshot, default=str, indent=1))
        return path
    except Exception:
        return None


class FlightRecorder:
    """Watchdog thread over a :class:`Progress` beacon.

    Env knobs (all read at construction, overridable per instance):

    - ``POLYAXON_TPU_WATCHDOG_K`` (8.0) — deadline = k × rolling median dt
    - ``POLYAXON_TPU_WATCHDOG_FLOOR_S`` (30.0) — deadline lower clamp
    - ``POLYAXON_TPU_WATCHDOG_CEILING_S`` (600.0) — deadline upper clamp
      (also the deadline before any dt sample exists)
    - ``POLYAXON_TPU_WATCHDOG_INTERVAL_S`` (1.0) — poll period; <= 0
      disables the thread entirely
    - ``POLYAXON_TPU_PROGRESS_INTERVAL_S`` (2.0) — min spacing of typed
      ``progress`` report lines

    One dump fires per stall episode (re-armed by the next beat), so a
    long hang costs one snapshot, not one per poll.
    """

    def __init__(
        self,
        progress: Optional[Progress] = None,
        *,
        reporter: Any = None,
        out_dir: Optional[Path] = None,
        process_id: int = 0,
        k: Optional[float] = None,
        floor_s: Optional[float] = None,
        ceiling_s: Optional[float] = None,
        interval_s: Optional[float] = None,
        progress_interval_s: Optional[float] = None,
    ) -> None:
        self.progress = progress if progress is not None else get_progress()
        self.reporter = reporter
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self.process_id = process_id
        self.k = k if k is not None else knob_float("POLYAXON_TPU_WATCHDOG_K")
        self.floor_s = (
            floor_s
            if floor_s is not None
            else knob_float("POLYAXON_TPU_WATCHDOG_FLOOR_S")
        )
        self.ceiling_s = (
            ceiling_s
            if ceiling_s is not None
            else knob_float("POLYAXON_TPU_WATCHDOG_CEILING_S")
        )
        self.interval_s = (
            interval_s
            if interval_s is not None
            else knob_float("POLYAXON_TPU_WATCHDOG_INTERVAL_S")
        )
        self.progress_interval_s = (
            progress_interval_s
            if progress_interval_s is not None
            else knob_float("POLYAXON_TPU_PROGRESS_INTERVAL_S")
        )
        self._seq = 0
        self._fired = False
        self._last_progress_emit = 0.0
        self._last_emitted_beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- deadline -------------------------------------------------------------
    def deadline_s(self, median_dt: Optional[float]) -> float:
        if median_dt is None:
            return self.ceiling_s
        return min(max(self.k * median_dt, self.floor_s), self.ceiling_s)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return
        self._thread = threading.Thread(
            target=self._loop, name="flightrec", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # Final progress flush: short runs finish between emit intervals,
        # and the control plane should still see their last step.
        self._emit_progress(force=True)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:
                # The watchdog must never take the worker down.
                pass

    # -- one poll -------------------------------------------------------------
    def check(self, now: Optional[float] = None) -> Optional[Path]:
        """Emit due progress, fire the stall dump when the deadline lapses.

        Returns the dump path when a dump fired (for tests); ``None``
        otherwise.
        """
        snap = self.progress.snapshot()
        if not snap["armed"]:
            return None
        self._emit_progress(snap=snap)
        age = snap["age_s"] or 0.0
        deadline = self.deadline_s(snap["median_dt_s"])
        if age <= deadline:
            self._fired = False
            return None
        if self._fired:
            return None
        self._fired = True
        return self.record(
            "stall",
            message=(
                f"no progress for {age:.1f}s "
                f"(deadline {deadline:.1f}s, step {snap['step']})"
            ),
            progress=snap,
            age_s=age,
            deadline_s=deadline,
            step=snap["step"],
        )

    def _emit_progress(
        self, snap: Optional[Dict[str, Any]] = None, force: bool = False
    ) -> None:
        if self.reporter is None:
            return
        snap = snap or self.progress.snapshot()
        if not snap["armed"]:
            return
        now = time.perf_counter()
        due = now - self._last_progress_emit >= self.progress_interval_s
        fresh = snap["beats"] != self._last_emitted_beats
        if not fresh or not (due or force):
            return
        self._last_progress_emit = now
        self._last_emitted_beats = snap["beats"]
        try:
            self.reporter.progress(
                step=snap["step"],
                epoch=snap["epoch"],
                throughput=snap["throughput"],
                at=snap["last_beat_at"],
            )
        except Exception:
            pass

    # -- forensics ------------------------------------------------------------
    def record(
        self,
        kind: str,
        *,
        message: Optional[str] = None,
        progress: Optional[Dict[str, Any]] = None,
        exc: Optional[BaseException] = None,
        **attrs: Any,
    ) -> Optional[Path]:
        """Dump a forensic snapshot + emit the typed ``anomaly`` line."""
        path: Optional[Path] = None
        if self.out_dir is not None:
            self._seq += 1
            path = dump_forensics(
                self.out_dir,
                self.process_id,
                self._seq,
                kind=kind,
                message=message,
                progress=progress or self.progress.snapshot(),
                report_path=getattr(self.reporter, "path", None),
                exc=exc,
            )
        if self.reporter is not None:
            try:
                self.reporter.anomaly(
                    kind,
                    message=message,
                    dump=str(path) if path else None,
                    # Run-relative artifact key (``reports/<file>``) so the
                    # anomaly row — and any alert built on it — links to
                    # the postmortem via the run artifacts API, not a path
                    # that only means something on the worker host.
                    dump_artifact=(
                        f"{self.out_dir.name}/{path.name}" if path else None
                    ),
                    **attrs,
                )
            except Exception:
                pass
        return path

    def crash_dump(self, exc: BaseException) -> Optional[Path]:
        """The entrypoint crash path: postmortem for every FAILED run."""
        return self.record(
            "crash",
            message=f"{type(exc).__name__}: {exc}",
            exc=exc,
        )
