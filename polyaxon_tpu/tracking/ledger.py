"""Per-run goodput & utilization ledger.

The reference platform reports *that* a run finished; it never answers
the two questions a TPU platform exists to answer — what fraction of
wall-clock was useful training (Google's ML-productivity "goodput"
metric) and what fraction of peak FLOPs the run sustained (PaLM-style
MFU).  Until now MFU lived only in ``bench.py``, out-of-band.

:class:`UtilizationLedger` is the worker-side accountant that makes both
first-class: it decomposes a run's wall clock into named buckets
(xla-compile, data-wait, step-compute, checkpoint-block, metric-drain,
idle), tracks model FLOPs per step (XLA cost analysis when available,
analytic estimates otherwise), HBM high-water marks, and XLA compile
telemetry from ``jax.monitoring`` record hooks (no-op on older JAX).
Rows flow as typed ``ledger`` report lines through the Reporter → the
GangWatcher ingests them into the registry's ``utilization`` table → the
API aggregates them gang-wide as ``GET /api/v1/runs/<id>/goodput``.

Process-wide singleton, same contract as ``trace.get_tracer()``:
workloads call :func:`get_ledger` and feed it; only the worker
entrypoint calls :func:`configure` to wire the report sink.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from polyaxon_tpu.conf.knobs import knob_float

__all__ = [
    "UtilizationLedger",
    "get_ledger",
    "configure",
    "install_compile_hooks",
    "compile_telemetry",
    "compile_cache_telemetry",
    "compiled_flops",
    "executable_flops",
    "transformer_flops_per_token",
    "conv_classifier_flops_per_image",
    "BUCKETS",
    "PEAK_FLOPS",
]

#: The wall-clock decomposition vocabulary.  Every ledger row's
#: ``buckets`` dict has exactly these keys; their sum equals the row's
#: ``wall_s`` (``idle_s`` is derived as the remainder, clamped at 0).
BUCKETS = (
    "xla_compile_s",
    "data_wait_s",
    "step_compute_s",
    "ckpt_block_s",
    "metric_drain_s",
    "idle_s",
)

#: bf16 peak FLOP/s per chip by PJRT device kind (dense MXU).  Shared
#: with ``bench.py`` so the platform's MFU and the benchmark's can never
#: disagree about the denominator.  Absent kinds (CPU, unknown TPUs)
#: resolve to no peak → MFU reports 0.0 rather than a made-up ratio.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

_UNSET = object()


# -- XLA compile telemetry (jax.monitoring record hooks) -----------------------

_compile_lock = threading.Lock()
_compile_seconds = 0.0
_compile_events = 0
_cache_hits = 0
_cache_misses = 0
_hooks_installed: Optional[bool] = None  # None = not yet attempted


def install_compile_hooks() -> bool:
    """Register ``jax.monitoring`` listeners for compile telemetry.

    Duration events under ``/jax/core/compile/`` (jaxpr trace, MLIR
    lowering, backend compile) accumulate into compile seconds; each
    ``compile_requests``/``cache_miss`` event counts one jit-cache miss.
    Idempotent; returns False — and stays a no-op — on JAX versions
    without the monitoring API.  Never imports jax itself: callers arm
    the ledger from workloads that already did.
    """
    global _hooks_installed
    if _hooks_installed is not None:
        return _hooks_installed
    if "jax" not in sys.modules:
        return False  # unattempted: a later start() after jax import retries
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw: Any) -> None:
            if "compile" in event:
                global _compile_seconds
                with _compile_lock:
                    _compile_seconds += float(duration)

        def _on_event(event: str, **kw: Any) -> None:
            # With the persistent cache armed (runtime/compilecache.py)
            # a cold compile fires BOTH compile_requests and cache_miss;
            # counting either-or (the pre-cache behaviour) would double
            # count, so requests carry compile_events and hit/miss feed
            # their own counters.
            global _compile_events, _cache_hits, _cache_misses
            if "cache_hit" in event:
                with _compile_lock:
                    _cache_hits += 1
            elif "cache_miss" in event:
                with _compile_lock:
                    _cache_misses += 1
            elif "compile_requests" in event:
                with _compile_lock:
                    _compile_events += 1

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _hooks_installed = True
    except Exception:
        _hooks_installed = False
    return _hooks_installed


def compile_telemetry() -> Tuple[float, int]:
    """(cumulative compile seconds, cumulative compile requests) so far."""
    with _compile_lock:
        return _compile_seconds, _compile_events


def compile_cache_telemetry() -> Tuple[int, int]:
    """(persistent-cache hits, misses) so far — both stay 0 when the
    cache is disabled or the jax version emits no cache events."""
    with _compile_lock:
        return _cache_hits, _cache_misses


# -- FLOPs accounting ----------------------------------------------------------

def executable_flops(compiled: Any) -> Optional[float]:
    """Total FLOPs from an ALREADY-COMPILED executable's cost analysis.

    The free probe: callers that AOT-compiled their step anyway
    (``runtime/compilecache.aot_compile``) get the number without paying
    a second compile.  Returns None when the object has no analysis
    (e.g. it is still a plain jitted fn because AOT fell back)."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = analysis.get("flops") if hasattr(analysis, "get") else None
        if flops is not None and float(flops) > 0:
            return float(flops)
    except Exception:
        pass
    return None


def compiled_flops(jitted: Callable, *args: Any) -> Optional[float]:
    """Total FLOPs of one compiled call, from XLA's cost analysis.

    ``jitted.lower(*args).compile()`` does NOT share the executable with
    later ``jitted(...)`` calls — probing costs one extra compile, which
    the compile hooks account honestly.  Returns None wherever the
    backend exposes no analysis (callers fall back to the analytic
    estimates below).
    """
    try:
        return executable_flops(jitted.lower(*args).compile())
    except Exception:
        return None


def transformer_flops_per_token(
    n_params: int, n_layers: int, n_heads: int, head_dim: int, seq: int
) -> float:
    """Train-step FLOPs per token: 6·N (fwd+bwd matmuls) + attention
    scores 12·L·H·hd·T (fwd+bwd, causal halves then doubles back) — the
    same accounting ``bench.py`` uses for its headline MFU."""
    return 6.0 * n_params + 12.0 * n_layers * n_heads * head_dim * seq


def conv_classifier_flops_per_image(
    image_size: int,
    in_channels: int,
    channels: Tuple[int, ...],
    dense_dim: int,
    n_classes: int,
) -> float:
    """Analytic train-step FLOPs per image for the builtin conv net
    (3x3 SAME convs + 2x2 maxpool per stage + dense head): 2 FLOPs per
    MAC forward, x3 for forward+backward."""
    flops = 0.0
    h = image_size
    cin = in_channels
    for cout in channels:
        flops += 2.0 * h * h * 9.0 * cin * cout
        h //= 2
        cin = cout
    flat = h * h * cin
    flops += 2.0 * flat * dense_dim + 2.0 * dense_dim * n_classes
    return 3.0 * flops


# -- the accountant ------------------------------------------------------------

class UtilizationLedger:
    """Wall-clock decomposition + live MFU accountant for one workload.

    Feeding is cheap (a lock + float adds): trainers call
    :meth:`step`/:meth:`account` per step and :meth:`maybe_flush` to
    emit a cumulative row at most every ``interval_s``; a final row with
    ``final=True`` goes out at workload exit.  Rows are cumulative
    (monotone totals, ``seq``-numbered) so the at-least-once report
    channel needs no dedup — consumers take the latest row per process.
    """

    def __init__(
        self,
        *,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        process_id: int = 0,
        interval_s: Optional[float] = None,
    ) -> None:
        self.sink = sink
        self.process_id = process_id
        if interval_s is None:
            interval_s = knob_float("POLYAXON_TPU_LEDGER_INTERVAL_S")
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.armed = False
        self.source = "train"
        self._t0_wall = 0.0
        self._p0 = 0.0
        self._acc: Dict[str, float] = {}
        self._step_wall_s = 0.0
        self.steps = 0
        self.tokens = 0
        self.flops = 0.0
        self._flops_per_step: Optional[float] = None
        self.devices = 0
        self.device_kind = ""
        self.peak_flops_per_s = 0.0
        self._hbm_peak_bytes = 0.0
        self._extra: Dict[str, Any] = {}
        self._seq = 0
        self._last_flush = 0.0
        self._compile0: Tuple[float, int] = (0.0, 0)
        self._cache0: Tuple[int, int] = (0, 0)
        self._compile_preloop: Optional[float] = None

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()

    def configure(
        self,
        *,
        sink: Any = _UNSET,
        process_id: Any = _UNSET,
        interval_s: Any = _UNSET,
    ) -> "UtilizationLedger":
        """In-place update (the worker entrypoint is the only caller) —
        workloads holding a :func:`get_ledger` reference see the sink."""
        with self._lock:
            if sink is not _UNSET:
                self.sink = sink
            if process_id is not _UNSET:
                self.process_id = process_id
            if interval_s is not _UNSET:
                self.interval_s = interval_s
        return self

    # -- arming ----------------------------------------------------------------

    def start(self, *, source: str = "train") -> "UtilizationLedger":
        """Arm at workload entry: reset totals, snapshot the compile
        counters (so back-to-back workloads in one process don't inherit
        each other's compile time), probe local devices for the peak-FLOPs
        denominator.  Installs the compile hooks if jax is importable."""
        install_compile_hooks()
        with self._lock:
            sink, process_id, interval = self.sink, self.process_id, self.interval_s
            self._reset_locked()
            self.sink, self.process_id, self.interval_s = sink, process_id, interval
            self.armed = True
            self.source = source
            self._t0_wall = time.time()
            self._p0 = time.perf_counter()
            self._last_flush = self._p0
            self._compile0 = compile_telemetry()
            self._cache0 = compile_cache_telemetry()
        if "jax" in sys.modules:
            try:
                import jax

                devices = jax.local_devices()
                with self._lock:
                    self.devices = len(devices)
                    self.device_kind = devices[0].device_kind if devices else ""
                    per_chip = PEAK_FLOPS.get(self.device_kind, 0.0)
                    self.peak_flops_per_s = per_chip * len(devices)
            except Exception:
                pass
        return self

    # -- feeding ---------------------------------------------------------------

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        with self._lock:
            self._flops_per_step = float(flops) if flops else None

    def mark_loop_start(self) -> None:
        """Everything compiled from here on happened *inside* the hot loop
        — and therefore inside measured step wall — so the snapshot
        subtracts it from step-compute (first-step jit, in-loop FLOPs
        probes).  Falls back to the first :meth:`step` call when never
        invoked, which mis-files the first step's own compile as
        step-compute; call this right before the loop."""
        compile_s, _ = compile_telemetry()
        with self._lock:
            if self._compile_preloop is None:
                self._compile_preloop = compile_s - self._compile0[0]

    def merge_extra(self, **extra: Any) -> None:
        """Workload-specific fields for the row's attrs (e.g. the serving
        engine's slot occupancy)."""
        with self._lock:
            self._extra.update(extra)

    def account(self, bucket: str, seconds: float) -> None:
        """Fold externally measured seconds into a named bucket."""
        if seconds and seconds > 0:
            with self._lock:
                self._acc[bucket] = self._acc.get(bucket, 0.0) + float(seconds)

    def step(
        self,
        dt: Optional[float] = None,
        *,
        tokens: int = 0,
        flops: Optional[float] = None,
    ) -> None:
        """One training/decode step: ``dt`` is its wall seconds (omit when
        the workload accounts ``step_compute_s`` explicitly), ``tokens``
        the examples/tokens it advanced."""
        compile_s, _ = compile_telemetry()
        with self._lock:
            if self._compile_preloop is None:
                # Compile seconds before the first step (jit_init, cost
                # probes) must not be subtracted from step wall below.
                self._compile_preloop = compile_s - self._compile0[0]
            self.steps += 1
            self.tokens += int(tokens)
            if dt is not None and dt > 0:
                self._step_wall_s += float(dt)
            if flops is not None:
                self.flops += float(flops)
            elif self._flops_per_step is not None:
                self.flops += self._flops_per_step

    def sample_hbm(self) -> float:
        """Refresh the HBM high-water mark from ``memory_stats()`` (0 on
        backends without memory telemetry — CPU, older PJRT)."""
        total = 0.0
        if "jax" in sys.modules:
            try:
                import jax

                for d in jax.local_devices():
                    try:
                        stats = d.memory_stats() or {}
                    except Exception:
                        stats = {}
                    peak = stats.get("peak_bytes_in_use")
                    if peak is None:
                        peak = stats.get("bytes_in_use")
                    if peak:
                        total += float(peak)
            except Exception:
                pass
        with self._lock:
            if total > self._hbm_peak_bytes:
                self._hbm_peak_bytes = total
            return self._hbm_peak_bytes

    # -- reading / emitting ----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative totals as one row: the bucket decomposition (summing
        to ``wall_s``), goodput ratio, MFU, throughput, compile and HBM
        telemetry."""
        compile_now, events_now = compile_telemetry()
        hits_now, misses_now = compile_cache_telemetry()
        with self._lock:
            wall = time.perf_counter() - self._p0 if self.armed else 0.0
            hooks_compile = max(0.0, compile_now - self._compile0[0])
            compile_s = hooks_compile + self._acc.get("xla_compile_s", 0.0)
            compile_events = max(0, events_now - self._compile0[1])
            data = self._acc.get("data_wait_s", 0.0)
            ckpt = self._acc.get("ckpt_block_s", 0.0)
            drain = self._acc.get("metric_drain_s", 0.0)
            step_compute = self._acc.get("step_compute_s", 0.0)
            if step_compute <= 0.0 and self._step_wall_s > 0.0:
                # Derive useful compute from step wall: subtract the waits
                # measured inside the loop and any compile that happened
                # after the first step (the first step's jit).
                in_loop_compile = max(
                    0.0, hooks_compile - (self._compile_preloop or 0.0)
                )
                step_compute = max(
                    0.0, self._step_wall_s - data - ckpt - in_loop_compile
                )
            idle = max(
                0.0, wall - (compile_s + data + step_compute + ckpt + drain)
            )
            # Clamped: sub-resolution timing jitter must not report >100%.
            goodput = min(1.0, step_compute / wall) if wall > 0 else 0.0
            mfu = (
                self.flops / (wall * self.peak_flops_per_s)
                if wall > 0 and self.peak_flops_per_s > 0
                else 0.0
            )
            tpds = (
                self.tokens / (wall * self.devices)
                if wall > 0 and self.devices > 0
                else 0.0
            )
            row: Dict[str, Any] = {
                "source": self.source,
                "process_id": self.process_id,
                "wall_s": wall,
                "buckets": {
                    "xla_compile_s": compile_s,
                    "data_wait_s": data,
                    "step_compute_s": step_compute,
                    "ckpt_block_s": ckpt,
                    "metric_drain_s": drain,
                    "idle_s": idle,
                },
                "steps": self.steps,
                "tokens": self.tokens,
                "flops": self.flops,
                "goodput": goodput,
                "mfu": mfu,
                "tokens_per_device_s": tpds,
                "compile_s": compile_s,
                "compile_events": compile_events,
                # Persistent-cache efficacy: how much of compile_s was a
                # disk read vs a cold XLA compile (registry folds these
                # into row attrs — no schema change).
                "compile_cache_hits": max(0, hits_now - self._cache0[0]),
                "compile_cache_misses": max(0, misses_now - self._cache0[1]),
                "hbm_peak_bytes": self._hbm_peak_bytes,
                "devices": self.devices,
                "device_kind": self.device_kind,
                "peak_flops_per_s": self.peak_flops_per_s,
            }
            if self._extra:
                row["extra"] = dict(self._extra)
            return row

    def maybe_flush(self) -> bool:
        """Throttled emit — call freely from hot loops."""
        if not self.armed or self.sink is None:
            return False
        now = time.perf_counter()
        with self._lock:
            if now - self._last_flush < self.interval_s:
                return False
        self.flush()
        return True

    def flush(self, final: bool = False) -> Optional[Dict[str, Any]]:
        """Emit one cumulative row through the sink (best-effort — the
        ledger must never be what kills a trainer)."""
        if not self.armed:
            return None
        self.sample_hbm()
        row = self.snapshot()
        with self._lock:
            self._seq += 1
            row["seq"] = self._seq
            self._last_flush = time.perf_counter()
        row["final"] = bool(final)
        if self.sink is not None:
            try:
                self.sink(row)
            except Exception:
                pass
        return row


_ledger = UtilizationLedger()


def get_ledger() -> UtilizationLedger:
    """The process-wide ledger (unconfigured: accounting only, no sink)."""
    return _ledger


def configure(**kwargs: Any) -> UtilizationLedger:
    """Configure the process-wide ledger (see :meth:`UtilizationLedger.configure`)."""
    return _ledger.configure(**kwargs)
