"""Single-chip training benchmark: flagship transformer LM on the real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The headline number is model FLOP/s utilization (MFU) of a bf16 train step
sized for one chip.  The reference publishes no training numbers
(BASELINE.md: "published": {}), so vs_baseline compares against the last
recorded run of THIS benchmark (BENCH_BASELINE.json, written on first run)
— i.e. the bar is "don't regress, then beat yourself".
"""

from __future__ import annotations

import json
import time
from pathlib import Path

# bf16 peak FLOP/s per chip by device kind (dense MXU) — single source of
# truth lives in the platform's utilization ledger so bench MFU and the
# in-product MFU can never disagree about the denominator.
from polyaxon_tpu.tracking.ledger import PEAK_FLOPS  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from polyaxon_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        param_axes,
    )
    from polyaxon_tpu.parallel import template_for
    from polyaxon_tpu.runtime.mesh import build_mesh
    from polyaxon_tpu.runtime.train import build_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    # Sized to exercise the MXU on one chip; tiny fallback for CPU smoke.
    if on_tpu:
        # Shape picked by measurement on v5e: d=2048/L=8 amortizes
        # non-matmul overhead; batch 20 is the r5 sweet spot (0.566 vs
        # 16:0.560, 18:0.564, 22:0.559, 24/32 spill/OOM); the save_attn
        # remat policy keeps the attention output across the bwd
        # recompute — full sweep in bench-notes. auto attention resolves
        # to the in-house flash kernel (1024-edge tiles), which beats XLA
        # dense at every measured T since the round-4 block sweep.
        cfg = TransformerConfig(
            vocab_size=32768,
            d_model=2048,
            n_layers=8,
            n_heads=32,
            head_dim=64,
            d_ff=8192,
            max_seq=1024,
            remat=True,
            remat_policy="save_attn",
        )
        batch_size, seq, steps, warmup = 20, 1024, 20, 3
    else:
        cfg = TransformerConfig(
            vocab_size=256,
            d_model=64,
            n_layers=2,
            n_heads=4,
            head_dim=16,
            d_ff=128,
            max_seq=64,
            dtype=jnp.float32,
        )
        batch_size, seq, steps, warmup = 4, 64, 5, 1

    mesh_axes = {"data": jax.local_device_count()}
    mesh = build_mesh(mesh_axes)
    template = template_for("ddp", mesh_axes)
    # bf16 first moment: halves adam-mu HBM traffic in the update step —
    # measured +2.6% MFU on v5e (0.529 → 0.543); loss curve unchanged at
    # bench scale (docs/bench-notes.md).
    optimizer = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    ts = build_train_step(
        loss_fn=lambda p, b: loss_fn(p, b, cfg, template=template, mesh=mesh),
        init_fn=lambda k: init_params(k, cfg),
        axes_tree=param_axes(cfg),
        optimizer=optimizer,
        mesh=mesh,
        template=template,
    )
    key = jax.random.PRNGKey(0)
    params, opt_state = ts.init(key)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, cfg.vocab_size, (batch_size, seq + 1))
    batch = ts.place_batch(
        {"tokens": jnp.asarray(tok[:, :-1]), "targets": jnp.asarray(tok[:, 1:])}
    )

    # Sync via a host read of the loss: on the axon (tunneled-TPU) platform
    # block_until_ready can return before remote execution finishes, which
    # made timings absurd; a device->host copy is a true barrier.
    for _ in range(warmup):
        params, opt_state, metrics = ts.step(params, opt_state, batch, key)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = ts.step(params, opt_state, batch, key)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    # Tracing overhead: the instrumented hot loop (per-step sampled span +
    # StepClock tick + histogram observe — exactly what the built-in
    # trainers add) vs the bare loop, same jitted step.  Gates the
    # observability layer's acceptance bar: tracing at default sampling
    # must cost <1% of step wall on the real device.
    trace_overhead_pct = None
    trace_overhead_ok = None
    try:
        from polyaxon_tpu.stats import MemoryStats
        from polyaxon_tpu.tracking.flightrec import Progress
        from polyaxon_tpu.tracking.profiling import StepClock
        from polyaxon_tpu.tracking.trace import get_tracer

        tracer = get_tracer()
        treg = MemoryStats()
        beacon = Progress()
        n_tr = min(steps, 10)

        # ts.step donates (params, opt_state), so every loop consumes the
        # state it is given and returns the live replacement.  The
        # instrumented side mirrors the built-in trainers exactly: span +
        # StepClock tick + histogram observe + stall-beacon beat, so the
        # watchdog's per-step cost is charged against the same budget.
        def _overhead_loop(n: int, instrumented: bool, p, o):
            clock = StepClock()
            clock.start()
            t0 = time.perf_counter()
            m = None
            for i in range(n):
                if instrumented:
                    with tracer.span("train:step", sample=tracer.hot_sample):
                        p, o, m = ts.step(p, o, batch, key)
                    d = clock.tick()
                    if d is not None:
                        treg.timing("train.step_wall_s", d)
                    beacon.beat(step=i)
                else:
                    p, o, m = ts.step(p, o, batch, key)
            float(m["loss"])
            return time.perf_counter() - t0, p, o

        _, params, opt_state = _overhead_loop(2, True, params, opt_state)
        plain = float("inf")
        instr = float("inf")
        for _ in range(3):
            d, params, opt_state = _overhead_loop(n_tr, False, params, opt_state)
            plain = min(plain, d)
        for _ in range(3):
            d, params, opt_state = _overhead_loop(n_tr, True, params, opt_state)
            instr = min(instr, d)
        trace_overhead_pct = max(0.0, (instr - plain) / plain * 100.0)
        # CPU-smoke steps are ~ms each, so scheduler noise dominates the
        # delta; the 1% bar is enforced where it means something (TPU).
        trace_budget_pct = 1.0 if on_tpu else 25.0
        trace_overhead_ok = trace_overhead_pct < trace_budget_pct
        if not trace_overhead_ok:
            import sys

            print(
                f"bench: trace_overhead_pct={trace_overhead_pct:.2f} exceeds "
                f"the {trace_budget_pct}% budget — tracing is taxing the "
                "hot loop",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    steps_per_s = steps / dt
    tokens_per_s = steps_per_s * batch_size * seq
    # Train-step FLOPs: 6*N per token (fwd+bwd matmuls) + attention scores
    # 12*L*H*hd*T per token (fwd+bwd, causal halves then doubles back).
    n_params = cfg.n_params
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.n_heads * cfg.head_dim * seq
    model_flops_per_s = tokens_per_s * flops_per_token
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12) * jax.local_device_count()
    mfu = model_flops_per_s / peak if on_tpu else 0.0

    # Second metric: LONG-CONTEXT capability+throughput. T=8192 is past the
    # dense path's memory wall on one v5e chip (dense OOMs at 25.7G); the
    # pallas flash kernel's O(T) memory makes the config runnable at all.
    final_loss = float(metrics["loss"])
    longctx = None
    if on_tpu:
        try:
            # Free the headline model's HBM first (params+adam ≈ 8G; the
            # long-context model needs the same again).
            del params, opt_state, batch, metrics
            import gc

            gc.collect()
            lcfg = cfg.scaled(max_seq=8192, attention_impl="flash")
            lts = build_train_step(
                loss_fn=lambda p, b: loss_fn(p, b, lcfg, template=template, mesh=mesh),
                init_fn=lambda k: init_params(k, lcfg),
                axes_tree=param_axes(lcfg),
                optimizer=optax.adamw(3e-4),
                mesh=mesh,
                template=template,
            )
            lparams, lopt = lts.init(key)
            ltok = rng.integers(0, lcfg.vocab_size, (2, 8192 + 1))
            lbatch = lts.place_batch(
                {"tokens": jnp.asarray(ltok[:, :-1]), "targets": jnp.asarray(ltok[:, 1:])}
            )
            for _ in range(2):
                lparams, lopt, lm = lts.step(lparams, lopt, lbatch, key)
            float(lm["loss"])
            lt0 = time.perf_counter()
            for _ in range(6):
                lparams, lopt, lm = lts.step(lparams, lopt, lbatch, key)
            float(lm["loss"])
            ldt = time.perf_counter() - lt0
            ltps = 6 * 2 * 8192 / ldt
            lfpt = 6 * lcfg.n_params + 12 * lcfg.n_layers * lcfg.n_heads * lcfg.head_dim * 8192
            longctx = {
                "tokens_per_s": round(ltps),
                "mfu": round(ltps * lfpt / peak, 4),
            }
            del lparams, lopt, lbatch
            gc.collect()
            # Capability stretch: T=16384 through the ring path on one
            # device (sp_ring, n=1 — the flash block kernel over the full
            # sequence inside the ring body). 2x the old context ceiling.
            rcfg = cfg.scaled(max_seq=16384, attention_impl="flash")
            rmesh_axes = {"sequence": 1}
            rmesh = build_mesh(rmesh_axes)
            rtmpl = template_for("sp_ring", rmesh_axes)
            rts = build_train_step(
                loss_fn=lambda p, b: loss_fn(p, b, rcfg, template=rtmpl, mesh=rmesh),
                init_fn=lambda k: init_params(k, rcfg),
                axes_tree=param_axes(rcfg),
                optimizer=optimizer,
                mesh=rmesh,
                template=rtmpl,
            )
            rparams, ropt = rts.init(key)
            rtok = rng.integers(0, rcfg.vocab_size, (1, 16384 + 1))
            rbatch = rts.place_batch(
                {"tokens": jnp.asarray(rtok[:, :-1]), "targets": jnp.asarray(rtok[:, 1:])}
            )
            for _ in range(2):
                rparams, ropt, rm = rts.step(rparams, ropt, rbatch, key)
            float(rm["loss"])
            rt0 = time.perf_counter()
            for _ in range(4):
                rparams, ropt, rm = rts.step(rparams, ropt, rbatch, key)
            float(rm["loss"])
            rdt = time.perf_counter() - rt0
            rtps = 4 * 16384 / rdt
            rfpt = 6 * rcfg.n_params + 12 * rcfg.n_layers * rcfg.n_heads * rcfg.head_dim * 16384
            # Honest label (r4 weak #4): this is the ring PATH exercised on
            # ONE chip ({sequence: 1} mesh) — a capability-stretch metric
            # (2x the dense context ceiling), not multi-device ring perf.
            longctx["t16384_single_chip_tokens_per_s"] = round(rtps)
            longctx["t16384_single_chip_mfu"] = round(rtps * rfpt / peak, 4)
            del rparams, ropt, rbatch
        except Exception:
            # null in the output = degraded gracefully, but the reason must
            # be visible (a flash-path regression is not an OOM).
            import sys
            import traceback

            traceback.print_exc(file=sys.stderr)

    # North-star #2 (BASELINE.md): hpsearch trials/hour — a real sweep
    # through the orchestrator (create → waves → iterate), workers as
    # subprocess gangs. Orchestration throughput, not model compute.
    # 16 trials / concurrency 4 (up from 6/2 in r≤4): one monitor tick no
    # longer moves the number double digits.
    trials_per_hour = None
    try:
        import tempfile

        from polyaxon_tpu.orchestrator import Orchestrator

        n_trials = 16
        orch = Orchestrator(
            tempfile.mkdtemp(), monitor_interval=0.05, heartbeat_interval=1.0
        )
        try:
            t0 = time.perf_counter()
            group = orch.submit(
                {
                    "kind": "group",
                    "run": {
                        "entrypoint": "polyaxon_tpu.builtins.trainers:metric_probe"
                    },
                    "environment": {
                        "topology": {
                            "accelerator": "cpu-1",
                            "num_devices": 1,
                            "num_hosts": 1,
                        }
                    },
                    "hptuning": {
                        "matrix": {"lr": {"uniform": [0, 1]}},
                        "concurrency": 4,
                        "random_search": {"n_experiments": n_trials, "seed": 0},
                    },
                }
            )
            done = orch.wait(group.id, timeout=300)
            sweep_dt = time.perf_counter() - t0
            if done.status == "succeeded":
                trials_per_hour = n_trials / sweep_dt * 3600
        finally:
            orch.stop()
    except Exception:
        pass

    # Stall-detection latency: a CPU-smoke gang whose train loop goes
    # silent mid-run (builtins stalling probe), measured through the REAL
    # path — worker beacon → progress report line → watcher ingest →
    # gang detector → anomaly row.  stall_detect_s is (anomaly row
    # created_at − last progress beat), i.e. injection→detection; the
    # budget is the detector threshold plus ingest/poll slack.
    stall_detect_s = None
    stall_detect_ok = None
    alert_fire_latency_s = None
    alert_fire_ok = None
    alert_tick_us = None
    alert_tick_overhead_ok = None
    try:
        import os
        import sys
        import tempfile

        from polyaxon_tpu.orchestrator import Orchestrator

        stall_after_s = 0.6
        knobs = {
            "POLYAXON_TPU_STALL_AFTER_S": str(stall_after_s),
            "POLYAXON_TPU_PROGRESS_INTERVAL_S": "0.05",
            "POLYAXON_TPU_WATCHDOG_INTERVAL_S": "0.05",
            "POLYAXON_TPU_WATCHDOG_FLOOR_S": "0.6",
            "POLYAXON_TPU_WATCHDOG_CEILING_S": "2.0",
            "POLYAXON_TPU_ALERT_INTERVAL_S": "0.05",
        }
        saved_env = {k: os.environ.get(k) for k in knobs}
        os.environ.update(knobs)
        orch = Orchestrator(
            tempfile.mkdtemp(), monitor_interval=0.05, heartbeat_interval=0.2
        )
        try:
            run = orch.submit(
                {
                    "kind": "experiment",
                    "run": {
                        "entrypoint": "polyaxon_tpu.builtins.trainers:stalling"
                    },
                    "declarations": {
                        "warm_steps": 10,
                        "beat_interval": 0.02,
                        "stall_s": 3.0,
                    },
                    "environment": {
                        "topology": {
                            "accelerator": "cpu-1",
                            "num_devices": 1,
                            "num_hosts": 1,
                        }
                    },
                }
            )
            orch.wait(run.id, timeout=120)
            stalls = orch.registry.get_anomalies(run.id, kind="stall")
            prog = orch.registry.get_progress(run.id)
            beats = [r["at"] for r in prog if r.get("at")]
            if stalls and beats:
                # First stall row from either detector (worker watchdog or
                # gang-level), whichever landed first.
                stall_detect_s = stalls[0]["created_at"] - max(
                    b for b in beats if b <= stalls[0]["created_at"]
                )
            # Alert-fire latency rides the same run: injection (last beat)
            # → detector → rule engine tick → FIRING row's fired_at.  The
            # run_stalled row is resolved at teardown but keeps fired_at.
            alerts = orch.registry.get_alerts(run.id, rule="run_stalled")
            if alerts and alerts[0]["fired_at"] and beats:
                fired_at = alerts[0]["fired_at"]
                before = [b for b in beats if b <= fired_at]
                if before:
                    alert_fire_latency_s = fired_at - max(before)
        finally:
            orch.stop()
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if stall_detect_s is not None:
            # Threshold + generous poll/ingest slack; must also fire while
            # the 3s stall is still in progress (else detection is moot).
            stall_detect_ok = 0.0 < stall_detect_s < stall_after_s + 2.5
            if not stall_detect_ok:
                print(
                    f"bench: stall_detect_s={stall_detect_s:.2f} outside "
                    f"budget ({stall_after_s} + 2.5s slack) — stall "
                    "detection is too slow",
                    file=sys.stderr,
                )
        else:
            print(
                "bench: stalling gang produced no stall anomaly row",
                file=sys.stderr,
            )
        if alert_fire_latency_s is not None:
            # Detection budget plus one engine tick of slack: the rule
            # engine rides the detector, it must not add seconds on top.
            alert_fire_ok = 0.0 < alert_fire_latency_s < stall_after_s + 3.0
            if not alert_fire_ok:
                print(
                    f"bench: alert_fire_latency_s={alert_fire_latency_s:.2f} "
                    f"outside budget ({stall_after_s} + 3.0s slack) — the "
                    "alert engine lags its detector",
                    file=sys.stderr,
                )
        else:
            print(
                "bench: stalling gang produced no firing run_stalled alert",
                file=sys.stderr,
            )

        # Idle-tick overhead: one full catalog evaluation over a healthy
        # run (no open alerts) must stay in microsecond territory — it
        # rides every monitor tick for every live gang forever.
        import pathlib

        from polyaxon_tpu.db.registry import RunRegistry
        from polyaxon_tpu.monitor.alerts import AlertEngine
        from polyaxon_tpu.stats.backends import MemoryStats

        idle_reg = RunRegistry(
            pathlib.Path(tempfile.mkdtemp()) / "bench-alerts.db"
        )
        try:
            idle_run = idle_reg.create_run(
                {
                    "kind": "experiment",
                    "run": {"entrypoint": "noop:main"},
                    "environment": {
                        "topology": {"accelerator": "cpu", "num_devices": 1}
                    },
                }
            )
            idle_engine = AlertEngine(
                idle_reg, stats=MemoryStats(), interval_s=0
            )
            idle_engine.evaluate(idle_run.id)  # warm sqlite/caches
            n_ticks = 200
            t0 = time.perf_counter()
            for _ in range(n_ticks):
                idle_engine.evaluate(idle_run.id)
            alert_tick_us = (time.perf_counter() - t0) / n_ticks * 1e6
        finally:
            idle_reg.close()
        alert_tick_overhead_ok = alert_tick_us < 5000.0
        if not alert_tick_overhead_ok:
            print(
                f"bench: alert_tick_us={alert_tick_us:.1f} over the 5ms "
                "budget — rule evaluation is taxing the monitor loop",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # On-demand profiling round trip, measured through the REAL path:
    # request_profile → command file in the worker mailbox → heartbeat
    # poll → windowed jax trace in the live train loop → capture report
    # line → watcher ingest → COMPLETE command row.  The budget covers
    # one heartbeat of delivery latency, the 3-step window, and ingest
    # slack.  Alongside it, the idle cost of the bus itself: a mailbox
    # poll with nothing queued must be microseconds — it rides every
    # worker heartbeat forever.
    profile_roundtrip_s = None
    profile_roundtrip_ok = None
    idle_bus_poll_us = None
    idle_bus_overhead_ok = None
    try:
        import sys
        import tempfile

        from polyaxon_tpu.db.registry import CommandStatus
        from polyaxon_tpu.orchestrator import Orchestrator
        from polyaxon_tpu.tracking.capture import CaptureAgent

        # Idle-bus overhead first (no gang needed): poll an empty mailbox
        # the way the Reporter heartbeat does.
        import pathlib

        idle_dir = pathlib.Path(tempfile.mkdtemp()) / "proc0"
        idle_dir.mkdir(parents=True)
        idle_agent = CaptureAgent().configure(
            reporter=None, mailbox=idle_dir, profiles_root=None, process_id=0
        )
        n_polls = 2000
        t0 = time.perf_counter()
        for _ in range(n_polls):
            idle_agent.poll()
        idle_bus_poll_us = (time.perf_counter() - t0) / n_polls * 1e6
        idle_bus_overhead_ok = idle_bus_poll_us < 500.0
        if not idle_bus_overhead_ok:
            print(
                f"bench: idle_bus_poll_us={idle_bus_poll_us:.1f} over the "
                "500us budget — the command mailbox is taxing every "
                "worker heartbeat",
                file=sys.stderr,
            )

        orch = Orchestrator(
            tempfile.mkdtemp(), monitor_interval=0.05, heartbeat_interval=0.2
        )
        try:
            run = orch.submit(
                {
                    "kind": "experiment",
                    "run": {
                        "entrypoint": "polyaxon_tpu.builtins.trainers:lm_train"
                    },
                    "declarations": {
                        "steps": 4000,
                        "batch": 4,
                        "seq": 64,
                        "vocab_size": 256,
                        "d_model": 64,
                        "n_layers": 2,
                        "n_heads": 4,
                        "head_dim": 16,
                        "d_ff": 128,
                    },
                    "environment": {
                        "topology": {
                            "accelerator": "cpu-1",
                            "num_devices": 1,
                            "num_hosts": 1,
                        }
                    },
                }
            )
            deadline = time.time() + 240
            stepping = False
            while time.time() < deadline:
                orch.pump(0.05)
                r = orch.registry.get_run(run.id)
                if r.is_done:
                    break
                prog = orch.registry.get_progress(run.id)
                if r.status == "running" and prog and prog[0]["step"] >= 1:
                    stepping = True
                    break
            if stepping:
                t0 = time.perf_counter()
                cmd = orch.request_profile(run.id, num_steps=3)
                deadline = time.time() + 60
                while time.time() < deadline:
                    orch.pump(0.05)
                    row = orch.registry.get_command(cmd["uuid"])
                    if row["status"] in CommandStatus.TERMINAL:
                        break
                if row["status"] == CommandStatus.COMPLETE:
                    caps = orch.registry.get_captures(
                        run.id, capture_id=cmd["capture_id"]
                    )
                    if caps and caps[0]["artifacts"]:
                        profile_roundtrip_s = time.perf_counter() - t0
                orch.stop_run(run.id)
                orch.wait(run.id, timeout=120)
        finally:
            orch.stop()
        if profile_roundtrip_s is not None:
            # heartbeat delivery (0.2s) + 3-step window + ingest slack.
            profile_roundtrip_ok = 0.0 < profile_roundtrip_s < 10.0
            if not profile_roundtrip_ok:
                print(
                    f"bench: profile_roundtrip_s={profile_roundtrip_s:.2f} "
                    "over the 10s budget — on-demand capture is too slow "
                    "to be an incident tool",
                    file=sys.stderr,
                )
        else:
            print(
                "bench: profile round trip produced no completed capture",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Ledger ground-truth check: run an lm_train smoke gang through the
    # REAL platform path (worker ledger → report line → watcher ingest →
    # goodput roll-up) and compare the platform's MFU against this
    # benchmark's own out-of-band computation for the same run (reported
    # tokens/s × analytic FLOPs/token ÷ the shared peak table).  Budget-
    # asserted like trace_overhead_pct, so the in-product number can
    # never silently drift from the benchmark's accounting.  The two
    # measure slightly different windows (the ledger's wall clock
    # includes model build + compile; reported tokens/s is loop-only), so
    # the budget is absolute-error with compile-amortization slack.
    reported_mfu_abs_err = None
    reported_mfu_ok = None
    first_step_s_cold = None
    first_step_s_warm = None
    first_step_warm_ok = None
    warm_cache_hits = None
    try:
        import sys
        import tempfile

        from polyaxon_tpu.monitor.watcher import goodput_status
        from polyaxon_tpu.orchestrator import Orchestrator

        orch = Orchestrator(
            tempfile.mkdtemp(), monitor_interval=0.05, heartbeat_interval=0.2
        )
        try:
            smoke_spec = {
                "kind": "experiment",
                "run": {
                    "entrypoint": "polyaxon_tpu.builtins.trainers:lm_train"
                },
                "declarations": {
                    "steps": 30,
                    "batch": 4,
                    "seq": 64,
                    "vocab_size": 256,
                    "d_model": 64,
                    "n_layers": 2,
                    "n_heads": 4,
                    "head_dim": 16,
                    "d_ff": 128,
                },
                "environment": {
                    "topology": {
                        "accelerator": "cpu-1",
                        "num_devices": 1,
                        "num_hosts": 1,
                    }
                },
            }
            run = orch.submit(smoke_spec)
            orch.wait(run.id, timeout=300)
            g = goodput_status(orch.registry, run.id)
            last = orch.registry.get_run(run.id).last_metric or {}
            # Cold/warm A/B on the SAME store layout: the first gang
            # compiled fresh and wrote the persistent compile cache; a
            # second, identical gang is a NEW worker process that should
            # load its step executable from disk instead of compiling.
            # first_step_s (AOT compile/cache-load + first step wall) is
            # the cold-start metric; the warm run must be materially
            # below the cold one and its ledger must show cache hits.
            run2 = orch.submit(smoke_spec)
            orch.wait(run2.id, timeout=300)
            g2 = goodput_status(orch.registry, run2.id)
            last2 = orch.registry.get_run(run2.id).last_metric or {}
        finally:
            orch.stop()
        first_step_s_cold = last.get("first_step_s")
        first_step_s_warm = last2.get("first_step_s")
        warm_cache_hits = g2.get("compile_cache_hits")
        if first_step_s_cold and first_step_s_warm:
            # Budget: the warm restart must recoup a real fraction of the
            # cold compile bill (cache load + dispatch isn't free, so not
            # ~0 — but well under a fresh compile).
            first_step_warm_ok = (
                first_step_s_warm < 0.8 * first_step_s_cold
                and (warm_cache_hits or 0) > 0
            )
            if not first_step_warm_ok:
                print(
                    f"bench: warm first_step_s={first_step_s_warm:.3f} "
                    f"(cache hits={warm_cache_hits}) did not materially "
                    f"beat cold first_step_s={first_step_s_cold:.3f} — "
                    "the persistent compile cache is not being reused "
                    "across worker processes",
                    file=sys.stderr,
                )
        if g["rows"] and g["wall_s"] > 0 and last.get("tokens_per_s"):
            smoke_peak = PEAK_FLOPS.get(g["device_kind"], 197e12) * max(
                1, g["devices"]
            )
            # Platform side: the ledger's FLOPs/wall accounting (its own
            # MFU is 0.0 off-TPU where peak is unknown — normalize both
            # sides by the same fallback peak so the check exercises the
            # numerator everywhere).
            platform_mfu = g["mfu"] or g["flops"] / (g["wall_s"] * smoke_peak)
            smoke_cfg = TransformerConfig(
                vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                head_dim=16, d_ff=128, max_seq=64,
            )
            smoke_fpt = (
                6 * smoke_cfg.n_params
                + 12 * smoke_cfg.n_layers * smoke_cfg.n_heads
                * smoke_cfg.head_dim * 64
            )
            bench_mfu = last["tokens_per_s"] * smoke_fpt / smoke_peak
            reported_mfu_abs_err = abs(platform_mfu - bench_mfu)
            mfu_budget = 0.15 if on_tpu else 0.05
            reported_mfu_ok = reported_mfu_abs_err <= mfu_budget
            if not reported_mfu_ok:
                print(
                    f"bench: reported_mfu_abs_err={reported_mfu_abs_err:.4f} "
                    f"exceeds the {mfu_budget} budget — the platform ledger "
                    "disagrees with the benchmark's MFU accounting",
                    file=sys.stderr,
                )
        else:
            print(
                "bench: lm_train smoke gang produced no usable ledger "
                f"roll-up (rows={g['rows']}, wall={g['wall_s']:.2f}, "
                f"tokens_per_s={last.get('tokens_per_s')})",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Goodput under injected failures: the remediation loop's reason to
    # exist, A/B'd through the REAL platform path.  Run A declares
    # save_every + restart_policy and gets SIGKILLed mid-loop; the
    # scheduler must auto-resume it from the latest complete async
    # checkpoint.  Run B is the no-remediation baseline (engine off, no
    # checkpoints): the blind restart re-pays every step.  The ratio is
    # step-accounted (useful steps / executed steps) so it is
    # deterministic under CI timing noise; recovery_s (failure decision →
    # back to RUNNING) carries the wall-clock side separately.
    run_goodput_ratio = None
    run_goodput_ok = None
    run_goodput_ratio_norestart = None
    recovery_s = None
    try:
        import os
        import sys
        import tempfile

        from polyaxon_tpu.db.registry import RemediationStatus
        from polyaxon_tpu.lifecycles import StatusOptions
        from polyaxon_tpu.orchestrator import Orchestrator

        fail_steps, fail_preempt = 24, 12

        def failure_spec(save_every):
            decls = {
                "steps": fail_steps,
                "preempt_step": fail_preempt,
                "batch": 4,
                "seq": 16,
                "vocab_size": 64,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "head_dim": 16,
                "d_ff": 64,
            }
            if save_every:
                decls["save_every"] = save_every
            return {
                "kind": "experiment",
                "run": {
                    "entrypoint": "polyaxon_tpu.builtins.trainers:lm_train"
                },
                "declarations": decls,
                "environment": {
                    "topology": {
                        "accelerator": "cpu-1",
                        "num_devices": 1,
                        "num_hosts": 1,
                    },
                    "restart_policy": {
                        "max_restarts": 1,
                        "backoff_seconds": 0.1,
                    },
                },
            }

        saved_rem_env = os.environ.get("POLYAXON_TPU_REMEDIATION_ENABLED")
        orch = Orchestrator(
            tempfile.mkdtemp(), monitor_interval=0.05, heartbeat_interval=0.2
        )
        try:
            os.environ["POLYAXON_TPU_REMEDIATION_ENABLED"] = "1"
            run_a = orch.submit(failure_spec(save_every=1))
            done_a = orch.wait(run_a.id, timeout=300)
            if done_a.status == StatusOptions.SUCCEEDED:
                rows = [
                    r
                    for r in orch.registry.get_remediations(
                        run_a.id, action="resume"
                    )
                    if r["status"] == RemediationStatus.SUCCEEDED
                ]
                from_step = (
                    rows[0]["attrs"].get("from_step") if rows else None
                )
                # Attempt 1 executed steps [0, preempt); attempt 2
                # resumed at from_step+1 and executed the rest.
                executed = fail_preempt + fail_steps
                if from_step is not None:
                    executed -= int(from_step) + 1
                run_goodput_ratio = fail_steps / max(1, executed)
                history = orch.registry.get_statuses(run_a.id)
                warn_ts = next(
                    (
                        s["created_at"]
                        for s in history
                        if s["status"] == StatusOptions.WARNING
                    ),
                    None,
                )
                if warn_ts is not None:
                    back = [
                        s["created_at"]
                        for s in history
                        if s["status"] == StatusOptions.RUNNING
                        and s["created_at"] > warn_ts
                    ]
                    if back:
                        recovery_s = back[0] - warn_ts
            else:
                print(
                    "bench: remediated run under injected failure did not "
                    f"complete (status={done_a.status})",
                    file=sys.stderr,
                )
            # Baseline: engine off, nothing to resume from — the restart
            # re-executes the whole schedule.
            os.environ["POLYAXON_TPU_REMEDIATION_ENABLED"] = "0"
            run_b = orch.submit(failure_spec(save_every=0))
            done_b = orch.wait(run_b.id, timeout=300)
            if done_b.status == StatusOptions.SUCCEEDED:
                run_goodput_ratio_norestart = fail_steps / (
                    fail_preempt + fail_steps
                )
        finally:
            orch.stop()
            if saved_rem_env is None:
                os.environ.pop("POLYAXON_TPU_REMEDIATION_ENABLED", None)
            else:
                os.environ["POLYAXON_TPU_REMEDIATION_ENABLED"] = saved_rem_env
        if run_goodput_ratio is not None:
            run_goodput_ok = run_goodput_ratio >= 0.5
            if not run_goodput_ok:
                print(
                    f"bench: run_goodput_ratio={run_goodput_ratio:.2f} under "
                    "the 0.5 floor — auto-resume is re-paying too much work "
                    "after an injected failure",
                    file=sys.stderr,
                )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Serving: the continuous-batching engine under CONCURRENT load vs the
    # same requests one-at-a-time through generate().  Decode is
    # memory-bound, so a batched slot step costs about what a B=1 step
    # does — the engine turns that slack into throughput.  Both sides are
    # warmed first (per prompt-length bucket) so this measures steady
    # state, not compilation.
    serving = None
    serving_ready_s = None
    try:
        from polyaxon_tpu.models import decode as decode_mod
        from polyaxon_tpu.serving import ServingEngine

        if on_tpu:
            scfg = TransformerConfig(
                vocab_size=32768,
                d_model=1024,
                n_layers=8,
                n_heads=16,
                head_dim=64,
                d_ff=4096,
                max_seq=1024,
            )
            n_req, max_new, slots = 16, 64, 8
        else:
            scfg = TransformerConfig(
                vocab_size=256,
                d_model=64,
                n_layers=2,
                n_heads=4,
                head_dim=16,
                d_ff=128,
                max_seq=128,
                dtype=jnp.float32,
            )
            n_req, max_new, slots = 8, 24, 4
        sparams = init_params(jax.random.PRNGKey(1), scfg)
        lengths = [6, 10, 14]
        prompts = [
            [int(x) for x in rng.integers(0, scfg.vocab_size, lengths[i % 3])]
            for i in range(n_req)
        ]
        # Offline reference: generate() jitted whole — the full decode
        # scan fused in one device call, no streaming, no admission.  An
        # upper bound the serving loop (which must return to the host
        # every step to stream tokens and admit work) does not get to
        # match; reported for context, not gated.
        import functools

        gen = jax.jit(
            functools.partial(
                decode_mod.generate, cfg=scfg, max_new_tokens=max_new
            )
        )
        for t in lengths:
            np.asarray(gen(sparams, jnp.asarray([[1] * t])))
        t0 = time.perf_counter()
        for p in prompts:
            np.asarray(gen(sparams, jnp.asarray([p])))
        offline_dt = time.perf_counter() - t0
        # Serving comparison — same regime both sides (per-step host loop,
        # streaming, admission): the SAME engine serving the SAME list
        # one-request-at-a-time vs all-at-once.  The delta is what
        # continuous batching itself buys.
        eng = ServingEngine(sparams, scfg, slots=slots, max_len=scfg.max_seq)
        t0 = time.perf_counter()
        eng.start()
        # Readiness gate: start() warms the whole bucket family in the
        # scheduler thread; ready means the first request compiles
        # nothing.  With the persistent cache primed by an earlier
        # process this is a disk load, not a compile.
        eng.wait_ready(timeout=600)
        serving_ready_s = time.perf_counter() - t0
        try:
            for t in lengths:
                eng.submit([1] * t, 2).wait(timeout=600)
            t0 = time.perf_counter()
            for p in prompts:
                eng.submit(p, max_new).wait(timeout=600)
            seq_dt = time.perf_counter() - t0
            t0 = time.perf_counter()
            reqs = [eng.submit(p, max_new) for p in prompts]
            for r in reqs:
                r.wait(timeout=600)
            conc_dt = time.perf_counter() - t0
        finally:
            eng.stop()
        # Fully-quantized serving: int8 weights (the PR 6 streaming path)
        # AND an int8 KV pool (rows + per-row scales) — same prompts,
        # same concurrent burst.  Decode is HBM-bandwidth-bound, so on
        # real hardware the int8 stream is the throughput story; on the
        # CPU smoke this is a correctness/steady-state check and the pool
        # byte ratio is the claim that transfers.
        qweights = decode_mod.quantize_weights(sparams)
        eng8 = ServingEngine(
            sparams, scfg, slots=slots, max_len=scfg.max_seq,
            qweights=qweights, kv_quantize="int8",
        ).start()
        eng8.wait_ready(timeout=600)
        try:
            for t in lengths:
                eng8.submit([1] * t, 2).wait(timeout=600)
            t0 = time.perf_counter()
            reqs = [eng8.submit(p, max_new) for p in prompts]
            for r in reqs:
                r.wait(timeout=600)
            conc8_dt = time.perf_counter() - t0
            int8_steady = eng8.stats()["steady_state_compiles"]
        finally:
            eng8.stop()
        total = n_req * max_new
        serving = {
            "tokens_per_s": round(total / conc_dt),
            "sequential_tokens_per_s": round(total / seq_dt),
            "speedup": round(seq_dt / conc_dt, 2),
            "offline_generate_tokens_per_s": round(total / offline_dt),
            "tokens_per_s_int8": round(total / conc8_dt),
            "int8_vs_f32": round(conc_dt / conc8_dt, 2),
            "kv_pool_bytes": eng.kv_pool_bytes,
            "kv_pool_bytes_int8": eng8.kv_pool_bytes,
            "kv_pool_ratio": round(eng8.kv_pool_bytes / eng.kv_pool_bytes, 3),
            "int8_steady_state_compiles": int8_steady,
            "n_requests": n_req,
            "slots": slots,
            "ready_s": round(serving_ready_s, 3),
        }
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Serving under LOAD: the same engine driven by Poisson arrivals at a
    # calibrated offered rate, chunked prefill vs full-prompt prefill.
    # The instant-burst number above can't see head-of-line blocking: a
    # short prompt that ARRIVES while a long prompt's monolithic prefill
    # is on the device waits the whole thing out; with chunked prefill it
    # waits at most one chunk (chunk boundaries are preemption points for
    # the shortest-remaining-first prefill queue).  Both sides get the
    # IDENTICAL arrival schedule (same seed), warmed pad buckets, and the
    # prefix cache off so the second run can't ride the first's KV.
    # The headline TTFT percentiles are over the SHORT (interactive)
    # class: chunking deliberately trades the long prompt's own TTFT for
    # everyone else's, so all-requests percentiles at small n are just
    # the slowest long both ways (bench-notes.md has the methodology).
    serving_loaded = None
    try:
        from polyaxon_tpu.serving import ServingEngine
        from polyaxon_tpu.serving.loadgen import poisson_load

        if on_tpu:
            lcfg, lparams = scfg, sparams
            long_len, short_len = 768, 16
            lmax_new, lchunk, n_loaded, lslots = 32, 128, 24, 8
        else:
            # The tiny smoke config's prefill is microseconds — too fast
            # for arrival overlap to be measurable above timer noise — so
            # the loaded A/B uses a config whose full-prompt prefill costs
            # real milliseconds on CPU.
            lcfg = TransformerConfig(
                vocab_size=256,
                d_model=256,
                n_layers=2,
                n_heads=4,
                head_dim=64,
                d_ff=1024,
                max_seq=512,
                dtype=jnp.float32,
            )
            lparams = init_params(jax.random.PRNGKey(2), lcfg)
            # 8 slots so admission never bottlenecks (a long request holds
            # its slot for its whole prefill; the A/B should measure
            # prefill head-of-line blocking, not slot scarcity).
            long_len, short_len = 480, 8
            lmax_new, lchunk, n_loaded, lslots = 4, 128, 24, 8
        loaded_prompts = [
            [
                int(x)
                for x in rng.integers(
                    0,
                    lcfg.vocab_size,
                    long_len if i % 3 == 0 else short_len,
                )
            ]
            for i in range(n_loaded)
        ]

        def loaded_run(prefill_chunk, rate_rps=None):
            eng = ServingEngine(
                lparams,
                lcfg,
                slots=lslots,
                max_len=lcfg.max_seq,
                prefill_chunk=prefill_chunk,
                prefix_cache=False,
            ).start()
            try:
                # Warm every prefill pad bucket + the decode step.
                for t in (long_len, short_len):
                    eng.submit([1] * t, 2).wait(timeout=600)
                if rate_rps is None:
                    # Calibrate the offered rate once, from this side's
                    # measured sequential service time.  This mix is
                    # PREFILL-bound (prefill is serialized on the device
                    # regardless of slot count), so capacity is ~1/svc,
                    # not slots/svc; offer 60% of it — genuinely loaded,
                    # but queues drain, so TTFT measures head-of-line
                    # blocking rather than raw queueing backlog.
                    t0 = time.perf_counter()
                    for p in loaded_prompts[:3]:
                        eng.submit(p, lmax_new).wait(timeout=600)
                    svc = (time.perf_counter() - t0) / 3
                    rate_rps = 0.6 / svc
                res = poisson_load(
                    eng,
                    loaded_prompts,
                    lmax_new,
                    rate_rps=rate_rps,
                    seed=17,
                )
            finally:
                eng.stop()
            return res, rate_rps

        full_res, lrate = loaded_run(None)
        chunked_res, _ = loaded_run(lchunk, rate_rps=lrate)

        from polyaxon_tpu.serving.loadgen import _pct

        def short_pct(res, q):
            vals = sorted(
                t
                for i, t in enumerate(res["ttft_s"])
                if i % 3 != 0 and t is not None
            )
            return _pct(vals, q)

        def long_mean(res):
            vals = [
                t
                for i, t in enumerate(res["ttft_s"])
                if i % 3 == 0 and t is not None
            ]
            return round(float(np.mean(vals)), 6) if vals else 0.0

        c_p99, f_p99 = short_pct(chunked_res, 99), short_pct(full_res, 99)
        serving_loaded = {
            "ttft_p99_s": c_p99,
            "ttft_p50_s": short_pct(chunked_res, 50),
            "tokens_per_s_loaded": chunked_res["tokens_per_s"],
            "full_prefill_ttft_p99_s": f_p99,
            "full_prefill_ttft_p50_s": short_pct(full_res, 50),
            "full_prefill_tokens_per_s": full_res["tokens_per_s"],
            "ttft_p99_speedup": (
                round(f_p99 / c_p99, 2) if c_p99 > 0 else None
            ),
            # The other side of the trade, reported so it can't hide:
            # the long prompts' own TTFT, which chunking makes WORSE.
            "long_ttft_mean_s": long_mean(chunked_res),
            "full_prefill_long_ttft_mean_s": long_mean(full_res),
            "all_ttft_p99_s": chunked_res["ttft_p99_s"],
            "full_prefill_all_ttft_p99_s": full_res["ttft_p99_s"],
            "offered_rps": round(lrate, 2),
            "prefill_chunk": lchunk,
            "n_requests": n_loaded,
            "completed": [chunked_res["completed"], full_res["completed"]],
            "errors": [chunked_res["errors"], full_res["errors"]],
        }
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Fixed-HBM A/B: the int8 KV pool's CAPACITY claim under load.  Same
    # mix, same Poisson schedule, but the pool is now the binding
    # resource: the f32 side gets ~1.5 long-request spans of blocks, so
    # two longs in flight contend (park, or shed on true deadlock); the
    # int8 side gets the SAME byte budget, which at (d+4) vs 4d bytes
    # per head-row holds >2x the blocks (``decode.kv_block_bytes`` is
    # the sizing primitive, test-pinned to the real leaf nbytes).
    # Completions / tokens-per-s / parks at equal HBM are the honest
    # comparison — this is "double the live batch at a fixed memory
    # budget" measured rather than asserted.
    serving_int8_kv = None
    try:
        if serving_loaded is None:
            raise RuntimeError(
                "loaded serving section did not run; skipping fixed-HBM A/B"
            )
        from polyaxon_tpu.models import decode as decode_mod

        ab_bs = 16
        span = -(-(long_len + lmax_new) // ab_bs)  # blocks one long spans
        kv_blocks_f32 = 1 + span + span // 2
        budget = kv_blocks_f32 * decode_mod.kv_block_bytes(lcfg, ab_bs)
        kv_blocks_int8 = int(
            budget // decode_mod.kv_block_bytes(lcfg, ab_bs, "int8")
        )

        def fixed_hbm_run(num_blocks, kv_quantize):
            eng = ServingEngine(
                lparams, lcfg, slots=lslots, max_len=lcfg.max_seq,
                block_size=ab_bs, num_blocks=num_blocks,
                prefill_chunk=lchunk, prefix_cache=False,
                kv_quantize=kv_quantize,
            ).start()
            try:
                for t in (long_len, short_len):
                    eng.submit([1] * t, 2).wait(timeout=600)
                res = poisson_load(
                    eng, loaded_prompts, lmax_new, rate_rps=lrate, seed=23
                )
                res["block_parks"] = eng.stats()["block_parks"]
                res["kv_pool_bytes"] = eng.kv_pool_bytes
            finally:
                eng.stop()
            return res

        ab_f32 = fixed_hbm_run(kv_blocks_f32, None)
        ab_int8 = fixed_hbm_run(kv_blocks_int8, "int8")
        serving_int8_kv = {  # [f32 pool, int8 pool] at equal pool bytes
            "kv_blocks": [kv_blocks_f32, kv_blocks_int8],
            "pool_bytes": [
                ab_f32["kv_pool_bytes"], ab_int8["kv_pool_bytes"]
            ],
            "tokens_per_s": [
                ab_f32["tokens_per_s"], ab_int8["tokens_per_s"]
            ],
            "completed": [ab_f32["completed"], ab_int8["completed"]],
            "errors": [ab_f32["errors"], ab_int8["errors"]],
            "block_parks": [ab_f32["block_parks"], ab_int8["block_parks"]],
            "ttft_p99_s": [ab_f32["ttft_p99_s"], ab_int8["ttft_p99_s"]],
            "offered_rps": round(lrate, 2),
            "n_requests": n_loaded,
        }
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Speculative-decoding A/B: templated (high n-gram self-overlap)
    # traffic through the same engine twice — spec off, then on — on the
    # identical Poisson schedule, offered above both arms' capacity so
    # tokens/s measures service capacity rather than the offered rate.
    # Decode-heavy on purpose (short prompts, LONG generations):
    # speculation buys nothing during prefill, and on a random-init
    # model the drafter's acceptance comes from greedy continuations
    # settling into short cycles ~100 tokens in, so the generation must
    # run long enough for the predictable tail to dominate — bench-notes
    # has the full methodology and why that mechanism is the honest CPU
    # stand-in for real templated traffic.  Greedy only (the engine's
    # spec scope), prefix cache off so arm 2 can't ride arm 1's KV,
    # warmup=True so the K-bucketed verify family compiles BEFORE the
    # clock starts — the same zero-steady-state-compiles discipline the
    # engine tests pin.  Gates on the tokens/s ratio AND unchanged
    # completion/error accounting: a speedup that drops requests is a
    # bug, not a win.
    serving_spec_decode = None
    try:
        from polyaxon_tpu.serving import ServingEngine
        from polyaxon_tpu.serving.loadgen import (
            poisson_load,
            templated_prompts,
        )

        # Small vocab + seed-0 params: the combination whose greedy
        # continuations reliably reach short cycles within the window.
        spec_cfg = TransformerConfig(
            vocab_size=64,
            d_model=64,
            n_layers=2,
            n_heads=4,
            head_dim=16,
            d_ff=256,
            max_seq=512,
            dtype=jnp.float32,
        )
        spec_params = init_params(jax.random.PRNGKey(0), spec_cfg)
        spec_max_new, spec_k, spec_slots = 448, 4, 4
        spec_prompts = templated_prompts(16, spec_cfg.vocab_size, seed=11)

        def spec_run(spec_on, rate_rps=None):
            eng = ServingEngine(
                spec_params, spec_cfg, slots=spec_slots,
                max_len=spec_cfg.max_seq, prefill_chunk=128,
                prefix_cache=False, warmup=True,
                spec_decode=spec_on, spec_k=spec_k, spec_min_ngram=2,
            ).start()
            try:
                if not eng.wait_ready(timeout=600):
                    raise RuntimeError("spec A/B engine warmup timed out")
                if rate_rps is None:
                    # Calibrate once, on THIS (spec-off) side: single-
                    # stream service time svc makes slots/svc the batch
                    # capacity ceiling; offer 2x that so both arms stay
                    # saturated and the makespan is service-bound.
                    t0 = time.perf_counter()
                    for p in spec_prompts[:3]:
                        eng.submit(p, spec_max_new).wait(timeout=600)
                    svc = (time.perf_counter() - t0) / 3
                    rate_rps = 2.0 * spec_slots / svc
                res = poisson_load(
                    eng, spec_prompts, spec_max_new,
                    rate_rps=rate_rps, seed=29,
                )
                s = eng.stats()
                res["spec_accept_rate"] = s["spec_accept_rate"]
                res["steady_state_compiles"] = s["steady_state_compiles"]
            finally:
                eng.stop()
            return res, rate_rps

        spec_off, spec_rate = spec_run(False)
        spec_on, _ = spec_run(True, rate_rps=spec_rate)
        spec_speedup = (
            round(spec_on["tokens_per_s"] / spec_off["tokens_per_s"], 3)
            if spec_off["tokens_per_s"] > 0
            else None
        )
        accounting_ok = (
            spec_on["completed"] == spec_off["completed"]
            and spec_on["errors"] == spec_off["errors"] == 0
        )
        serving_spec_decode = {  # [spec off, spec on]
            "tokens_per_s": [
                spec_off["tokens_per_s"], spec_on["tokens_per_s"]
            ],
            "speedup": spec_speedup,
            "speedup_ok": (
                spec_speedup is not None and spec_speedup >= 1.5
            ),
            "accounting_ok": accounting_ok,
            "completed": [spec_off["completed"], spec_on["completed"]],
            "errors": [spec_off["errors"], spec_on["errors"]],
            "spec_accept_rate": spec_on["spec_accept_rate"],
            "steady_state_compiles": [
                spec_off["steady_state_compiles"],
                spec_on["steady_state_compiles"],
            ],
            "spec_k": spec_k,
            "max_new_tokens": spec_max_new,
            "offered_rps": round(spec_rate, 2),
            "n_requests": len(spec_prompts),
        }
        if not (serving_spec_decode["speedup_ok"] and accounting_ok):
            import sys

            print(
                f"bench: serving_spec_decode gate failed: {serving_spec_decode}",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Hierarchical KV A/B: the host offload tier's AVAILABILITY claim at
    # fixed HBM.  Same mix and seeded Poisson schedule as the loaded
    # section, but the pool is ONE long span + one block — any two longs
    # in flight (or a long mid-prefill beside a parked decoder) want ~2x
    # the pool.  Offload OFF, a parked sequence sits on its blocks and
    # contention resolves by deadlock-shedding; offload ON, parking
    # SPILLS the blocks to pinned host memory, so the same schedule
    # absorbs with zero sheds — oversubscription now costs restore
    # latency (the bounded-TTFT number) instead of availability.
    # warmup=True so the export/import fns compile before the clock
    # starts: the gate includes steady_state_compiles == 0 WITH
    # spill/restore active.
    serving_kv_offload = None
    try:
        if serving_loaded is None:
            raise RuntimeError(
                "loaded serving section did not run; skipping KV offload A/B"
            )
        ob_bs = 16
        ospan = -(-(long_len + lmax_new) // ob_bs)  # blocks one long spans
        kv_blocks_sub = 1 + ospan + 1  # trash + one long span + one block
        # 2x the loaded section's calibrated rate: the pool-contention
        # window (two longs in flight) must open RELIABLY, not by
        # arrival luck — at 0.6 utilization the off arm can dodge it.
        orate = 2.0 * lrate

        def offload_run(kv_offload):
            eng = ServingEngine(
                lparams, lcfg, slots=lslots, max_len=lcfg.max_seq,
                block_size=ob_bs, num_blocks=kv_blocks_sub,
                prefill_chunk=lchunk, prefix_cache=False, warmup=True,
                kv_offload=kv_offload,
            ).start()
            try:
                if not eng.wait_ready(timeout=600):
                    raise RuntimeError("KV offload A/B warmup timed out")
                res = poisson_load(
                    eng, loaded_prompts, lmax_new, rate_rps=orate, seed=23
                )
                s = eng.stats()
                for k in (
                    "block_parks",
                    "host_spilled_blocks_total",
                    "host_restored_blocks_total",
                    "steady_state_compiles",
                ):
                    res[k] = s[k]
            finally:
                eng.stop()
            return res

        kv_off = offload_run(False)
        kv_on = offload_run(True)
        usable = kv_blocks_sub - 1
        serving_kv_offload = {  # [offload off, offload on]
            "kv_blocks": kv_blocks_sub,
            "long_span_blocks": ospan,
            "oversubscription_x": round(2 * ospan / usable, 2),
            "completed": [kv_off["completed"], kv_on["completed"]],
            "sheds": [kv_off["sheds"], kv_on["sheds"]],
            "errors": [kv_off["errors"], kv_on["errors"]],
            "block_parks": [
                kv_off["block_parks"], kv_on["block_parks"]
            ],
            "spilled_blocks": [
                kv_off["host_spilled_blocks_total"],
                kv_on["host_spilled_blocks_total"],
            ],
            "restored_blocks": [
                kv_off["host_restored_blocks_total"],
                kv_on["host_restored_blocks_total"],
            ],
            "ttft_p99_s": [kv_off["ttft_p99_s"], kv_on["ttft_p99_s"]],
            "tokens_per_s": [
                kv_off["tokens_per_s"], kv_on["tokens_per_s"]
            ],
            "steady_state_compiles": [
                kv_off["steady_state_compiles"],
                kv_on["steady_state_compiles"],
            ],
            "zero_sheds_ok": (
                kv_on["sheds"] == 0
                and kv_on["errors"] == 0
                and kv_on["completed"] == n_loaded
            ),
            "spill_active_ok": (
                kv_on["host_spilled_blocks_total"] > 0
                and kv_on["steady_state_compiles"] == 0
            ),
            "offered_rps": round(orate, 2),
            "n_requests": n_loaded,
        }
        if not (
            serving_kv_offload["zero_sheds_ok"]
            and serving_kv_offload["spill_active_ok"]
        ):
            import sys

            print(
                f"bench: serving_kv_offload gate failed: {serving_kv_offload}",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Warm replica boot: the persistent prefix store's TTFT claim.  An
    # incumbent engine serves the shared prefixes once and persists its
    # hot prefix blocks on stop; a COLD and a WARM replacement then face
    # the IDENTICAL seeded schedule of prefix+tail traffic.  The warm
    # replica preloaded the prefixes during warmup, so its first
    # requests skip the long prefix prefill — exactly the chaos
    # scale-up scenario (the autoscaler's replacement boots while the
    # fleet is most loaded).  Greedy parity probes pin that the warm KV
    # is the SAME KV: outputs warm vs cold must be token-identical.
    serving_warm_boot = None
    try:
        import tempfile

        if serving_loaded is None:
            raise RuntimeError(
                "loaded serving section did not run; skipping warm-boot A/B"
            )
        wb_bs = 16
        n_pref, pref_len, tail_len, wb_max_new = 2, 240, 8, 4
        wrng = np.random.default_rng(47)
        wb_prefixes = [
            [int(x) for x in wrng.integers(0, lcfg.vocab_size, pref_len)]
            for _ in range(n_pref)
        ]
        wb_prompts = [
            wb_prefixes[i % n_pref]
            + [int(x) for x in wrng.integers(0, lcfg.vocab_size, tail_len)]
            for i in range(12)
        ]
        wb_probe = wb_prefixes[0] + [3, 1, 4, 1, 5, 9, 2, 6]
        wb_blocks = 96  # preload budget (96-1)//2 = 47 >= the 30 stored

        def wb_engine(persist_dir):
            return ServingEngine(
                lparams, lcfg, slots=lslots, max_len=lcfg.max_seq,
                block_size=wb_bs, num_blocks=wb_blocks,
                prefill_chunk=lchunk, prefix_cache=True, warmup=True,
                kv_persist_dir=persist_dir, kv_persist_sig="bench",
                kv_persist_blocks=48,
            )

        with tempfile.TemporaryDirectory() as wb_dir:
            # Incumbent: compute + persist the shared prefixes.
            inc = wb_engine(wb_dir).start()
            try:
                if not inc.wait_ready(timeout=600):
                    raise RuntimeError("warm-boot incumbent warmup timed out")
                t0 = time.perf_counter()
                for pref in wb_prefixes:
                    inc.submit(list(pref), wb_max_new).wait(timeout=600)
                wb_svc = (time.perf_counter() - t0) / n_pref
            finally:
                inc.stop()  # final persist happens here
            wb_rate = 0.6 / wb_svc

            def replacement_run(persist_dir):
                eng = wb_engine(persist_dir).start()
                try:
                    if not eng.wait_ready(timeout=600):
                        raise RuntimeError("warm-boot arm warmup timed out")
                    preloaded = eng.stats()["kv_preloaded_blocks"]
                    res = poisson_load(
                        eng, wb_prompts, wb_max_new,
                        rate_rps=wb_rate, seed=31,
                    )
                    res["kv_preloaded_blocks"] = preloaded
                    res["prefix_cache_hit_rate"] = eng.stats()[
                        "prefix_cache_hit_rate"
                    ]
                    res["probe_tokens"] = eng.submit(
                        list(wb_probe), wb_max_new
                    ).wait(timeout=600)
                finally:
                    eng.stop()
                return res

            cold = replacement_run(None)
            warm = replacement_run(wb_dir)
        token_identical = cold["probe_tokens"] == warm["probe_tokens"]
        serving_warm_boot = {  # [cold boot, warm boot]
            "kv_preloaded_blocks": [
                cold["kv_preloaded_blocks"], warm["kv_preloaded_blocks"]
            ],
            "first_requests_ttft_p99_s": [
                cold["ttft_p99_s"], warm["ttft_p99_s"]
            ],
            "first_requests_ttft_mean_s": [
                cold["ttft_mean_s"], warm["ttft_mean_s"]
            ],
            "prefix_cache_hit_rate": [
                cold["prefix_cache_hit_rate"], warm["prefix_cache_hit_rate"]
            ],
            "completed": [cold["completed"], warm["completed"]],
            "errors": [cold["errors"], warm["errors"]],
            "ttft_p99_speedup": (
                round(cold["ttft_p99_s"] / warm["ttft_p99_s"], 2)
                if warm["ttft_p99_s"] > 0
                else None
            ),
            "token_identical": token_identical,
            "warm_boot_ok": (
                token_identical
                and warm["kv_preloaded_blocks"] > 0
                and cold["kv_preloaded_blocks"] == 0
                and warm["ttft_p99_s"] < cold["ttft_p99_s"]
            ),
            "offered_rps": round(wb_rate, 2),
            "n_requests": len(wb_prompts),
        }
        if not serving_warm_boot["warm_boot_ok"]:
            import sys

            print(
                f"bench: serving_warm_boot gate failed: {serving_warm_boot}",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Training input pipeline: the overlapped hot loop (host prefetch +
    # device prefetch + async metrics, runtime/pipeline.py) vs the same
    # loop fully synchronous, on a dataset-backed image-classifier config.
    # On CPU smoke the fixture lives in page cache and the box may have 1
    # core, so the data path has no REAL latency for overlap to hide —
    # `io_delay_ms` injects a simulated per-batch storage RTT (sleep, no
    # CPU) on the shared thunk stream, applied identically to BOTH sides.
    # On TPU the delay is 0: gather + H2D genuinely overlap device compute.
    train_images = None
    try:
        import tempfile

        from polyaxon_tpu.models import cnn
        from polyaxon_tpu.runtime.data import global_batch_from_host_data
        from polyaxon_tpu.runtime.datasets import (
            DatasetReader,
            make_image_fixture,
        )
        from polyaxon_tpu.runtime.pipeline import MetricsDrain, TrainPipeline

        if on_tpu:
            t_batch, t_img, t_ch = 256, 64, (64, 128, 256)
            t_steps, t_warm, t_examples, io_delay_ms = 40, 5, 8192, 0.0
        else:
            t_batch, t_img, t_ch = 128, 16, (8, 16)
            t_steps, t_warm, t_examples, io_delay_ms = 24, 3, 2048, 15.0
        t_dir = tempfile.mkdtemp()
        make_image_fixture(
            t_dir, "bench-images",
            num_examples=t_examples, image_size=t_img, shards=4, seed=0,
        )
        t_cfg = cnn.CNNConfig(
            image_size=t_img, n_classes=10, channels=t_ch
        )

        def t_loss(p, b):
            images = b["images"].astype(t_cfg.dtype) / 255.0 - 0.5
            return cnn.loss_fn(p, {**b, "images": images}, t_cfg)

        t_ts = build_train_step(
            loss_fn=t_loss,
            init_fn=lambda k: cnn.init_params(k, t_cfg),
            axes_tree=cnn.param_axes(t_cfg),
            optimizer=optax.adamw(1e-3),
            mesh=mesh,
            template=template,
        )

        def t_place(local):
            return global_batch_from_host_data(
                {
                    "images": local["images"],
                    "labels": local["labels"].astype(np.int32),
                },
                t_ts.batch_sharding,
            )

        def t_source(reader):
            for task in reader.batch_tasks(0):
                yield (
                    lambda t=task: (time.sleep(io_delay_ms / 1e3), t())[1]
                    if io_delay_ms
                    else t()
                )

        def t_run(overlap: bool):
            t_params, t_opt = t_ts.init(jax.random.PRNGKey(0))
            reader = DatasetReader(
                t_dir, "bench-images", global_batch=t_batch, seed=0
            )
            pipe = TrainPipeline(
                t_source(reader), t_place,
                prefetch=3 if overlap else 0, workers=2,
            )
            drain = MetricsDrain(lambda s, v: None) if overlap else None
            m = None
            try:
                for _ in range(t_warm):
                    b = next(pipe)
                    t_params, t_opt, m = t_ts.step(t_params, t_opt, b, None)
                jax.block_until_ready(t_params)
                wait0 = pipe.data_wait_s
                t0 = time.perf_counter()
                for i in range(t_steps):
                    b = next(pipe)
                    t_params, t_opt, m = t_ts.step(t_params, t_opt, b, None)
                    # Logging convention per side: the sync loop pays the
                    # host read inline (the old trainers' shape), the
                    # overlapped loop pushes the device array to the drain.
                    if i % 10 == 0:
                        if overlap:
                            drain.push(i, {"loss": m["loss"]})
                        else:
                            float(m["loss"])
                jax.block_until_ready(t_params)  # fence BEFORE timing
                dt = time.perf_counter() - t0
            finally:
                pipe.close()
                if drain is not None:
                    drain.close()
            ips = t_steps * t_batch / dt
            wait_ms = (pipe.data_wait_s - wait0) / t_steps * 1e3
            return ips, wait_ms

        off_ips, off_wait = t_run(False)
        on_ips, on_wait = t_run(True)
        train_images = {
            "images_per_s": round(on_ips),
            "sync_images_per_s": round(off_ips),
            "speedup": round(on_ips / off_ips, 2),
            "data_wait_ms_per_step": round(on_wait, 2),
            "sync_data_wait_ms_per_step": round(off_wait, 2),
            "batch": t_batch,
            "io_delay_ms": io_delay_ms,
        }
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Fleet serving: N real replica subprocesses behind the FleetRouter.
    # Two claims, measured not asserted: (1) aggregate decode throughput
    # scales with replicas (same seeded burst offered to N=1 and N=2 —
    # the router's least-loaded spread is what's under test), and
    # (2) SIGKILLing a replica mid-load loses NOTHING: every request
    # completes (failover) or gets exactly one typed error — zero hangs,
    # zero silent drops — and TTFT recovers once the dead replica is
    # ejected.  The same fleet serves both N=2 arms (throughput first,
    # then the destructive failover arm), so the bench pays 2 boots.
    serving_fleet = None
    serving_fleet_failover = None
    try:
        import os
        import tempfile
        import threading
        from http.server import ThreadingHTTPServer

        from polyaxon_tpu.serving.fleet import LocalServingFleet
        from polyaxon_tpu.serving.loadgen import (
            http_poisson_load,
            shared_prefix_prompts,
        )
        from polyaxon_tpu.serving.router import FleetRouter, make_router_handler

        fmodel = {
            "vocab_size": 64, "d_model": 32, "n_layers": 2,
            "n_heads": 4, "head_dim": 8, "d_ff": 64,
        }
        fl_n_req, fl_max_new = (48, 24) if on_tpu else (24, 16)
        fl_prompts = shared_prefix_prompts(
            fl_n_req, fmodel["vocab_size"],
            prefix_len=8, suffix_len=8, groups=4, seed=11,
        )

        def fleet_warm(fl):
            # One request straight at EVERY replica (bypassing the
            # router) before the timed run: concurrent cold compiles
            # otherwise thrash the host and both arms measure XLA's
            # compile queue instead of the router's spread.
            import urllib.request

            for wname in list(fl._procs):
                wrep = fl.router.replica(wname)
                wbody = json.dumps(
                    {
                        "prompts": [fl_prompts[0]],
                        "max_new_tokens": fl_max_new * 2,
                    }
                ).encode()
                wreq = urllib.request.Request(
                    wrep.base_url + "/generate",
                    data=wbody,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(wreq, timeout=300) as wr:
                    wr.read()

        def fleet_up(n):
            # Occupancy shedding is OFF for the throughput arms: a shed
            # under the deliberate burst would let the N=1 arm drop work
            # and fake a flat scaleup.  The failover arm re-enables it.
            router = FleetRouter(
                probe_interval_s=0.2, probe_timeout_s=1.0,
                request_timeout_s=300.0, retry_limit=2,
                eject_failures=2, eject_backoff_s=0.5,
                shed_occupancy=1e9,
            )
            fl = LocalServingFleet(
                Path(tempfile.mkdtemp()), fmodel,
                replicas=n, seq=64, slots=4, seed=0, router=router,
                env={"POLYAXON_TPU_SERVING_WARMUP": "0"},
            )
            fl.start()
            if not fl.wait_ready(timeout_s=180):
                fl.stop()
                raise RuntimeError(f"{n}-replica fleet never became ready")
            handler = make_router_handler(router, {"fleet_name": "bench"})
            front = ThreadingHTTPServer(("127.0.0.1", 0), handler)
            threading.Thread(target=front.serve_forever, daemon=True).start()
            url = f"http://127.0.0.1:{front.server_address[1]}"
            return fl, front, url

        def fleet_down(fl, front):
            front.shutdown()
            front.server_close()
            fl.stop()

        # Arm A: single replica, seeded burst (rate >> capacity, so wall
        # is service-bound, not schedule-bound — the only regime where
        # replica count can show up in tokens/s at all).
        fl1, front1, url1 = fleet_up(1)
        try:
            fleet_warm(fl1)
            res1 = http_poisson_load(
                url1, fl_prompts, fl_max_new,
                rate_rps=200.0, seed=11, timeout_s=300.0,
            )
        finally:
            fleet_down(fl1, front1)

        # Arm B: two replicas, byte-identical prompt set and schedule.
        fl2, front2, url2 = fleet_up(2)
        try:
            fleet_warm(fl2)
            res2 = http_poisson_load(
                url2, fl_prompts, fl_max_new,
                rate_rps=200.0, seed=11, timeout_s=300.0,
            )
            scaleup = (
                round(res2["tokens_per_s"] / res1["tokens_per_s"], 3)
                if res1["tokens_per_s"] > 0 else None
            )
            # The >1.5x claim needs cores for the second replica to run
            # ON — two CPU-bound processes can't beat one core.  On a
            # starved smoke box the gate degrades to no-collapse (the
            # router must not serialize the fleet below a lone replica's
            # floor); multi-core CI and TPU hosts enforce the real bar.
            fl_cores = os.cpu_count() or 1
            fl_gate = 1.5 if fl_cores >= 3 else 0.5
            serving_fleet = {  # [N=1, N=2] on the same offered burst
                "tokens_per_s": [res1["tokens_per_s"], res2["tokens_per_s"]],
                "scaleup": scaleup,
                "scaleup_gate": fl_gate,
                "scaleup_ok": scaleup is not None and scaleup > fl_gate,
                "cores": fl_cores,
                "completed": [res1["completed"], res2["completed"]],
                "hangs": [res1["hangs"], res2["hangs"]],
                "ttft_p99_s": [res1["ttft_p99_s"], res2["ttft_p99_s"]],
                "n_requests": fl_n_req,
                "max_new_tokens": fl_max_new,
            }

            # Arm C (same fleet, now warm): SIGKILL one replica mid-load.
            # Longer decodes keep requests in flight at the kill point.
            victim = next(iter(fl2._procs))
            resf = http_poisson_load(
                url2, fl_prompts, fl_max_new * 2,
                rate_rps=200.0, seed=13, timeout_s=300.0,
                kill_at_s={victim: max(0.5, res2["wall_s"] * 0.3)},
                fleet=fl2,
            )
            accounted = resf["completed"] + resf["sheds"] + resf["errors"]
            # TTFT of the tail third — sent after the kill landed — shows
            # whether routing recovered or late requests starved.
            tail = [
                t for t in resf["ttft_s"][-(fl_n_req // 3):] if t is not None
            ]
            rc = fl2.router.stats()["counters"]
            serving_fleet_failover = {
                "n_requests": resf["n_requests"],
                "completed": resf["completed"],
                "sheds": resf["sheds"],
                "typed_errors": resf["errors"],
                "failures": resf["failures"],
                "hangs": resf["hangs"],
                # The contract: every request accounted for, none hung.
                "zero_lost": (
                    accounted == resf["n_requests"]
                    and resf["hangs"] == 0
                    and resf["failures"] == 0
                ),
                "ttft_p99_s": resf["ttft_p99_s"],
                "tail_ttft_p99_s": (
                    round(max(tail), 6) if tail else None
                ),
                "tail_completed": len(tail),
                "router_failovers": rc["failovers"],
                "router_retries": rc["retries"],
                "router_ejections": rc["ejections"],
                "kill_at_s": round(max(0.5, res2["wall_s"] * 0.3), 3),
            }
        finally:
            fleet_down(fl2, front2)
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Autoscale under chaos: the availability proof for the PR 15
    # closed loop.  A 1-replica fleet with the FleetAutoscaler attached
    # faces a seeded chaos schedule — an overload ramp (sustained sheds
    # → scale-up), a mid-ramp SIGKILL of a serving replica (reap →
    # capacity repair), a same-rate recovery phase, then an idle tail
    # (drain back to min).  Claims: (1) ZERO lost requests — every
    # request completes or ends typed, no hangs — across all of it;
    # (2) the recovery-phase shed+error fraction drops well below the
    # overload phase's once the autoscaler restores capacity; (3) the
    # fleet returns to min_replicas with the autoscaler idle.
    serving_autoscale_chaos = None
    try:
        import tempfile
        import threading
        from http.server import ThreadingHTTPServer

        from polyaxon_tpu.serving.fleet import LocalServingFleet
        from polyaxon_tpu.serving.loadgen import ChaosEvent, chaos_poisson_load
        from polyaxon_tpu.serving.router import FleetRouter, make_router_handler

        acmodel = {
            "vocab_size": 64, "d_model": 16, "n_layers": 1,
            "n_heads": 2, "head_dim": 8, "d_ff": 32,
        }
        ac_router = FleetRouter(
            probe_interval_s=0.1, probe_timeout_s=1.0,
            request_timeout_s=120.0, retry_limit=2,
            eject_failures=2, eject_backoff_s=0.5,
            shed_occupancy=0.8,
        )
        ac_fleet = LocalServingFleet(
            Path(tempfile.mkdtemp()), acmodel,
            replicas=1, seq=64, slots=2, seed=0, router=ac_router,
            env={"POLYAXON_TPU_SERVING_WARMUP": "0"},
        )
        ac_fleet.start()
        try:
            if not ac_fleet.wait_ready(timeout_s=180):
                raise RuntimeError("autoscale-chaos fleet never became ready")
            ac_scaler = ac_fleet.attach_autoscaler(
                enabled=True, shed_rate=0.25, idle_occupancy=0.3,
                min_replicas=1, max_replicas=2,
                up_hold_s=1.0, down_hold_s=1.0,
                up_cooldown_s=1.0, down_cooldown_s=2.0,
                budget=8,
            )
            ac_handler = make_router_handler(
                ac_router, {"fleet_name": "autoscale-chaos"}
            )
            ac_front = ThreadingHTTPServer(("127.0.0.1", 0), ac_handler)
            threading.Thread(
                target=ac_front.serve_forever, daemon=True
            ).start()
            ac_url = f"http://127.0.0.1:{ac_front.server_address[1]}"
            try:
                ac_res = chaos_poisson_load(
                    ac_url,
                    [[i % 60, (i + 7) % 60, (i + 21) % 60, (i + 33) % 60]
                     for i in range(12)],
                    8,
                    phases=[
                        (6.0, 8.0),   # overload ramp on 1 replica
                        (20.0, 8.0),  # sustain: scale-up + kill repair
                        (8.0, 8.0),   # recovery: capacity restored
                        (8.0, 0.0),   # idle tail: drain back to min
                    ],
                    seed=17,
                    events=[ChaosEvent(3.0, "kill")],  # mid-ramp SIGKILL
                    fleet=ac_fleet,
                    pump=ac_fleet.poll,
                    pump_interval_s=0.05,
                    timeout_s=300.0,
                )
                # Drain-down may still be in flight when the load tail
                # ends — keep pumping the control loop until it settles.
                settle_deadline = time.time() + 90.0
                while time.time() < settle_deadline:
                    ac_fleet.poll()
                    if (
                        ac_router.stats()["n_ready"] == 1
                        and len(ac_fleet._procs) == 1
                        and ac_scaler.status()["state"] == "idle"
                    ):
                        break
                    time.sleep(0.05)
                accounted = (
                    ac_res["completed"] + ac_res["sheds"]
                    + ac_res["errors"] + ac_res["failures"]
                )
                overload = ac_res["by_phase"][0]
                recovery = ac_res["by_phase"][2]
                shed_frac = lambda p: (  # noqa: E731
                    (p["sheds"] + p["errors"]) / p["n"] if p["n"] else None
                )
                st = ac_scaler.status()
                serving_autoscale_chaos = {
                    "n_requests": ac_res["n_requests"],
                    "completed": ac_res["completed"],
                    "sheds": ac_res["sheds"],
                    "typed_errors": ac_res["errors"],
                    "failures": ac_res["failures"],
                    "hangs": ac_res["hangs"],
                    # The contract: every request accounted for, none
                    # hung — through scale-up, SIGKILL, and drain-down.
                    "zero_lost": (
                        accounted == ac_res["n_requests"]
                        and ac_res["hangs"] == 0
                        and ac_res["failures"] == 0
                    ),
                    "by_phase": ac_res["by_phase"],
                    "overload_shed_frac": shed_frac(overload),
                    "recovered_shed_frac": shed_frac(recovery),
                    "shed_recovered": (
                        shed_frac(recovery) is not None
                        and shed_frac(recovery) < 0.3
                    ),
                    "decisions_spent": ac_scaler.decisions_spent,
                    "back_to_min": (
                        ac_router.stats()["n_ready"] == 1
                        and len(ac_fleet._procs) == 1
                        and st["state"] == "idle"
                        and st["target_replicas"] == 1
                    ),
                    "last_decision": st["last_decision"],
                }
            finally:
                ac_front.shutdown()
                ac_front.server_close()
        finally:
            ac_fleet.stop()
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Request-tracing overhead gate + waterfall completeness: the same
    # saturated engine drives an A/B with per-request distributed
    # tracing on vs off (root span, phase spans, waterfall build,
    # exemplar ring).  Throughput-based on purpose — a fixed-rate
    # Poisson arm's wall clock is set by the schedule, which would hide
    # the overhead being measured.  The traced arm also checks the
    # waterfall contract: interval-based phases must sum to the
    # client-observed request latency (within 10%), which hot-sampled
    # per-step spans cannot break by construction.
    serving_trace_overhead_pct = None
    serving_trace_overhead_ok = None
    serving_waterfall_err_pct = None
    serving_waterfall_ok = None
    try:
        from polyaxon_tpu.serving import ServingEngine as _TrEngine
        from polyaxon_tpu.tracking.trace import TraceContext, new_trace_id

        tr_max_new = 16
        tr_prompts = [
            [int(x) for x in rng.integers(0, scfg.vocab_size, 24)]
            for _ in range(16)
        ]

        def trace_run(traced):
            eng = _TrEngine(
                sparams, scfg, slots=4, max_len=scfg.max_seq,
                prefix_cache=False,
            ).start()
            try:
                eng.trace_requests = traced
                eng.submit([1] * 24, 2).wait(timeout=600)  # warm buckets
                t0 = time.perf_counter()
                pending = []
                for p in tr_prompts:
                    pending.append(
                        (
                            eng.submit(
                                p,
                                tr_max_new,
                                trace=(
                                    TraceContext(new_trace_id())
                                    if traced
                                    else None
                                ),
                            ),
                            time.perf_counter(),
                        )
                    )
                errs = []
                for r, ts in pending:
                    r.wait(timeout=600)
                    lat = time.perf_counter() - ts
                    summary = r.trace_summary
                    if summary is not None and lat > 0:
                        phase_sum = sum(summary["waterfall"].values())
                        errs.append(abs(phase_sum - lat) / lat * 100.0)
                wall = time.perf_counter() - t0
            finally:
                eng.stop()
            return wall, errs

        # Interleaved reps; min-wall per arm shrugs off scheduler noise.
        walls = {True: [], False: []}
        wf_errs = []
        for _ in range(2):
            for traced in (False, True):
                wall, errs = trace_run(traced)
                walls[traced].append(wall)
                if traced:
                    wf_errs.extend(errs)
        off, on = min(walls[False]), min(walls[True])
        serving_trace_overhead_pct = max(0.0, (on - off) / off * 100.0)
        serving_trace_budget_pct = 3.0 if on_tpu else 25.0
        serving_trace_overhead_ok = (
            serving_trace_overhead_pct < serving_trace_budget_pct
        )
        if not serving_trace_overhead_ok:
            import sys

            print(
                f"bench: serving_trace_overhead_pct="
                f"{serving_trace_overhead_pct:.2f} exceeds the "
                f"{serving_trace_budget_pct}% budget — request tracing "
                f"is taxing the serving engine",
                file=sys.stderr,
            )
        if wf_errs:
            serving_waterfall_err_pct = max(wf_errs)
            serving_waterfall_ok = serving_waterfall_err_pct <= 10.0
            if not serving_waterfall_ok:
                import sys

                print(
                    f"bench: waterfall phases off by "
                    f"{serving_waterfall_err_pct:.1f}% from "
                    f"client-observed latency (> 10%) — the phase "
                    f"intervals no longer partition the request",
                    file=sys.stderr,
                )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    baseline_path = Path(__file__).parent / "BENCH_BASELINE.json"
    vs_baseline = 1.0
    longctx_vs_baseline = None
    hpsearch_vs_baseline = None
    serving_vs_baseline = None
    serving_int8_vs_baseline = None
    serving_loaded_vs_baseline = None
    serving_spec_vs_baseline = None
    serving_fleet_vs_baseline = None
    train_images_vs_baseline = None
    if on_tpu:
        base = json.loads(baseline_path.read_text()) if baseline_path.exists() else {}
        if base.get("tokens_per_s"):
            vs_baseline = tokens_per_s / base["tokens_per_s"]
        else:
            base["tokens_per_s"], base["mfu"] = tokens_per_s, mfu
        # The long-context metric is baselined too (round-3 weak #5: a
        # flash regression must not ship silently behind the headline).
        if longctx is not None:
            if base.get("longctx_tokens_per_s"):
                longctx_vs_baseline = round(
                    longctx["tokens_per_s"] / base["longctx_tokens_per_s"], 3
                )
            else:
                base["longctx_tokens_per_s"] = longctx["tokens_per_s"]
        # hpsearch trials/hour gates too (r4 weak #2: a 13.5% regression
        # shipped silently because only tokens/s and longctx were gated).
        if trials_per_hour is not None:
            if base.get("hpsearch_trials_per_hour"):
                hpsearch_vs_baseline = round(
                    trials_per_hour / base["hpsearch_trials_per_hour"], 3
                )
            else:
                base["hpsearch_trials_per_hour"] = round(trials_per_hour)
        # Serving throughput gates like the rest: a scheduler or slot-step
        # regression must not hide behind an unchanged training headline.
        if serving is not None:
            if base.get("serving_tokens_per_s"):
                serving_vs_baseline = round(
                    serving["tokens_per_s"] / base["serving_tokens_per_s"], 3
                )
            else:
                base["serving_tokens_per_s"] = serving["tokens_per_s"]
        # The quantized serving path gates on its own baseline — an int8
        # dequant-fusion regression must not hide behind the f32 number.
        if serving is not None and serving.get("tokens_per_s_int8"):
            if base.get("serving_tokens_per_s_int8"):
                serving_int8_vs_baseline = round(
                    serving["tokens_per_s_int8"]
                    / base["serving_tokens_per_s_int8"],
                    3,
                )
            else:
                base["serving_tokens_per_s_int8"] = serving[
                    "tokens_per_s_int8"
                ]
        # Loaded serving throughput gates separately — paging/prefill
        # regressions show up here before the instant-burst number moves.
        if serving_loaded is not None:
            if base.get("serving_tokens_per_s_loaded"):
                serving_loaded_vs_baseline = round(
                    serving_loaded["tokens_per_s_loaded"]
                    / base["serving_tokens_per_s_loaded"],
                    3,
                )
            else:
                base["serving_tokens_per_s_loaded"] = serving_loaded[
                    "tokens_per_s_loaded"
                ]
        # The speculative arm gates on its own baseline: a drafter or
        # verify-kernel regression must not hide behind the unchanged
        # non-speculative loaded number.
        if serving_spec_decode is not None:
            if base.get("serving_spec_tokens_per_s"):
                serving_spec_vs_baseline = round(
                    serving_spec_decode["tokens_per_s"][1]
                    / base["serving_spec_tokens_per_s"],
                    3,
                )
            else:
                base["serving_spec_tokens_per_s"] = serving_spec_decode[
                    "tokens_per_s"
                ][1]
        # Fleet aggregate throughput gates on the N=2 arm — a router or
        # balancing regression shows up here even when the single-engine
        # serving numbers are unchanged.
        if serving_fleet is not None:
            if base.get("serving_fleet_tokens_per_s"):
                serving_fleet_vs_baseline = round(
                    serving_fleet["tokens_per_s"][1]
                    / base["serving_fleet_tokens_per_s"],
                    3,
                )
            else:
                base["serving_fleet_tokens_per_s"] = serving_fleet[
                    "tokens_per_s"
                ][1]
        # The overlapped train input path gates like serving: a prefetch
        # or async-checkpoint regression must not hide behind an unchanged
        # (synthetic-data) training headline.
        if train_images is not None:
            if base.get("train_images_per_s"):
                train_images_vs_baseline = round(
                    train_images["images_per_s"] / base["train_images_per_s"],
                    3,
                )
            else:
                base["train_images_per_s"] = train_images["images_per_s"]
        baseline_path.write_text(json.dumps(base))

    # Control-plane saturation: the flight instruments under load.  A
    # ~1000-run registry, 8 fake gangs streaming report lines, and a
    # concurrent API hammer run simultaneously while one gang stalls
    # mid-flight — gating on watcher ingest-lag p99 (is the tail keeping
    # up with the writers), stall→alert fire latency beyond the
    # configured threshold (does detection survive saturation), and API
    # read p99 under full ingest.  The idle-tick measure is the
    # instrumentation overhead floor, held to the same 5ms budget as
    # alert_tick_us.
    controlplane_saturation = None
    cp_watcher_lag_p99_ok = None
    cp_alert_fire_ok = None
    cp_api_p99_ok = None
    cp_idle_tick_us = None
    cp_idle_tick_ok = None
    try:
        import sys
        import tempfile

        from polyaxon_tpu.monitor.cploadgen import (
            measure_idle_tick_us,
            run_saturation,
        )

        controlplane_saturation = run_saturation(
            tempfile.mkdtemp(),
            n_registry_runs=1000,
            n_gangs=8,
            procs_per_gang=2,
            duration_s=6.0,
            write_hz=20.0,
            api_concurrency=4,
            stall_after_s=0.75,
            monitor_interval_s=0.05,
        )
        cp_lag_p99 = controlplane_saturation["watcher_ingest_lag_p99_s"]
        cp_fire_s = controlplane_saturation["alert_fire_latency_s"]
        cp_api_p99 = controlplane_saturation["api_p99_s"]
        # Budgets: ingest lag tracks the write cadence (50ms monitor tick
        # + 50ms writer period ≪ 1s), the stall alert must fire within 2s
        # of first becoming fireable, and API reads must stay interactive
        # while every gang's reports drain through the same process.
        cp_watcher_lag_p99_ok = cp_lag_p99 is not None and cp_lag_p99 < 1.0
        cp_alert_fire_ok = cp_fire_s is not None and cp_fire_s < 2.0
        cp_api_p99_ok = cp_api_p99 is not None and cp_api_p99 < 0.25
        if not cp_watcher_lag_p99_ok:
            print(
                f"bench: watcher_ingest_lag_p99_s={cp_lag_p99} over the 1s "
                "budget — the watcher tail is not keeping up with ingest",
                file=sys.stderr,
            )
        if not cp_alert_fire_ok:
            print(
                f"bench: cp alert_fire_latency_s={cp_fire_s} over the 2s "
                "budget — stall detection degrades under saturation",
                file=sys.stderr,
            )
        if not cp_api_p99_ok:
            print(
                f"bench: api_p99_s={cp_api_p99} over the 250ms budget — "
                "API reads degrade under concurrent ingest",
                file=sys.stderr,
            )
        if controlplane_saturation.get("api_errors"):
            print(
                f"bench: {controlplane_saturation['api_errors']} API errors "
                "during the saturation hammer",
                file=sys.stderr,
            )
        cp_idle_tick_us = measure_idle_tick_us(tempfile.mkdtemp(), iters=200)
        cp_idle_tick_ok = cp_idle_tick_us < 5000.0
        if not cp_idle_tick_ok:
            print(
                f"bench: cp_idle_tick_us={cp_idle_tick_us:.1f} over the 5ms "
                "budget — tick instrumentation costs too much when idle",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # Metric-history scrape overhead: the scrape phase rides the monitor
    # tick, so it gets a share-of-tick budget (amortised at the
    # production scrape:tick cadence ratio) — and the query API must
    # stay interactive against a populated registry while scrapes and
    # report ingest run concurrently.
    metrics_scrape_overhead = None
    scrape_share_ok = None
    metrics_query_p99_ok = None
    try:
        import sys
        import tempfile

        from polyaxon_tpu.monitor.cploadgen import run_scrape_overhead

        metrics_scrape_overhead = run_scrape_overhead(
            tempfile.mkdtemp(),
            n_registry_runs=1000,
            n_replicas=16,
            n_gangs=4,
            duration_s=4.0,
            monitor_interval_s=0.05,
            api_duration_s=2.0,
            api_concurrency=2,
        )
        scrape_share = metrics_scrape_overhead["scrape_share"]
        query_p99 = metrics_scrape_overhead["query_p99_s"]
        scrape_share_ok = scrape_share is not None and scrape_share < 0.10
        metrics_query_p99_ok = query_p99 is not None and query_p99 < 0.1
        if not scrape_share_ok:
            print(
                f"bench: scrape_share={scrape_share} over the 10% budget — "
                "the metric scrape phase is taxing the monitor tick",
                file=sys.stderr,
            )
        if not metrics_query_p99_ok:
            print(
                f"bench: metrics query_p99_s={query_p99} over the 100ms "
                "budget on a 1000-run registry",
                file=sys.stderr,
            )
        if metrics_scrape_overhead.get("query_errors"):
            print(
                f"bench: {metrics_scrape_overhead['query_errors']} metric "
                "query errors during the overhead hammer",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    # graft-lint full-package runtime: the static pass rides every CI
    # invocation (`make lint` is in the gate), so it gets a wall-clock
    # budget like every other tick path — a rule that grows a quadratic
    # project index fails here, not in everyone's pre-push loop.
    analysis_runtime_s = None
    analysis_runtime_ok = None
    try:
        import sys

        from polyaxon_tpu.analysis import run_analysis

        t0 = time.perf_counter()
        run_analysis()
        analysis_runtime_s = time.perf_counter() - t0
        analysis_runtime_ok = analysis_runtime_s < 10.0
        if not analysis_runtime_ok:
            print(
                f"bench: analysis_runtime_s={analysis_runtime_s:.2f} over "
                "the 10s budget — graft-lint is too slow for CI",
                file=sys.stderr,
            )
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "lm_train_single_chip_mfu",
                "value": round(mfu, 4),
                "unit": "mfu",
                "vs_baseline": round(vs_baseline, 3),
                "tokens_per_s": round(tokens_per_s),
                "steps_per_s": round(steps_per_s, 3),
                "final_loss": round(final_loss, 4),
                "device": dev.device_kind,
                "n_params": n_params,
                "hpsearch_trials_per_hour": (
                    round(trials_per_hour) if trials_per_hour else None
                ),
                "hpsearch_vs_baseline": hpsearch_vs_baseline,
                "longctx_flash_t8192": longctx,
                "longctx_vs_baseline": longctx_vs_baseline,
                "serving_tokens_per_s": serving,
                "serving_vs_baseline": serving_vs_baseline,
                "serving_tokens_per_s_int8": (
                    serving.get("tokens_per_s_int8") if serving else None
                ),
                "serving_int8_vs_baseline": serving_int8_vs_baseline,
                "serving_int8_kv": serving_int8_kv,
                "serving_ttft_p99_s": (
                    serving_loaded["ttft_p99_s"] if serving_loaded else None
                ),
                "serving_tokens_per_s_loaded": (
                    serving_loaded["tokens_per_s_loaded"]
                    if serving_loaded
                    else None
                ),
                "serving_loaded": serving_loaded,
                "serving_loaded_vs_baseline": serving_loaded_vs_baseline,
                "serving_spec_decode": serving_spec_decode,
                "serving_spec_vs_baseline": serving_spec_vs_baseline,
                "serving_kv_offload": serving_kv_offload,
                "serving_warm_boot": serving_warm_boot,
                "serving_fleet_tokens_per_s": serving_fleet,
                "serving_fleet_vs_baseline": serving_fleet_vs_baseline,
                "serving_fleet_failover": serving_fleet_failover,
                "serving_autoscale_under_chaos": serving_autoscale_chaos,
                "train_images_per_s": train_images,
                "train_images_vs_baseline": train_images_vs_baseline,
                "trace_overhead_pct": (
                    round(trace_overhead_pct, 2)
                    if trace_overhead_pct is not None
                    else None
                ),
                "trace_overhead_ok": trace_overhead_ok,
                "serving_trace_overhead_pct": (
                    round(serving_trace_overhead_pct, 2)
                    if serving_trace_overhead_pct is not None
                    else None
                ),
                "serving_trace_overhead_ok": serving_trace_overhead_ok,
                "serving_waterfall_err_pct": (
                    round(serving_waterfall_err_pct, 2)
                    if serving_waterfall_err_pct is not None
                    else None
                ),
                "serving_waterfall_ok": serving_waterfall_ok,
                "stall_detect_s": (
                    round(stall_detect_s, 2)
                    if stall_detect_s is not None
                    else None
                ),
                "stall_detect_ok": stall_detect_ok,
                "alert_fire_latency_s": (
                    round(alert_fire_latency_s, 2)
                    if alert_fire_latency_s is not None
                    else None
                ),
                "alert_fire_ok": alert_fire_ok,
                "alert_tick_us": (
                    round(alert_tick_us, 1)
                    if alert_tick_us is not None
                    else None
                ),
                "alert_tick_overhead_ok": alert_tick_overhead_ok,
                "profile_roundtrip_s": (
                    round(profile_roundtrip_s, 2)
                    if profile_roundtrip_s is not None
                    else None
                ),
                "profile_roundtrip_ok": profile_roundtrip_ok,
                "idle_bus_poll_us": (
                    round(idle_bus_poll_us, 1)
                    if idle_bus_poll_us is not None
                    else None
                ),
                "idle_bus_overhead_ok": idle_bus_overhead_ok,
                "reported_mfu_abs_err": (
                    round(reported_mfu_abs_err, 5)
                    if reported_mfu_abs_err is not None
                    else None
                ),
                "reported_mfu_ok": reported_mfu_ok,
                "first_step_s_cold": (
                    round(first_step_s_cold, 3)
                    if first_step_s_cold is not None
                    else None
                ),
                "first_step_s_warm": (
                    round(first_step_s_warm, 3)
                    if first_step_s_warm is not None
                    else None
                ),
                "first_step_warm_ok": first_step_warm_ok,
                "compile_cache_hits_warm": warm_cache_hits,
                "run_goodput_ratio": (
                    round(run_goodput_ratio, 3)
                    if run_goodput_ratio is not None
                    else None
                ),
                "run_goodput_ok": run_goodput_ok,
                "run_goodput_ratio_norestart": (
                    round(run_goodput_ratio_norestart, 3)
                    if run_goodput_ratio_norestart is not None
                    else None
                ),
                "recovery_s": (
                    round(recovery_s, 2) if recovery_s is not None else None
                ),
                "serving_ready_s": (
                    round(serving_ready_s, 3)
                    if serving_ready_s is not None
                    else None
                ),
                "controlplane_saturation": controlplane_saturation,
                "cp_watcher_lag_p99_ok": cp_watcher_lag_p99_ok,
                "cp_alert_fire_ok": cp_alert_fire_ok,
                "cp_api_p99_ok": cp_api_p99_ok,
                "cp_idle_tick_us": (
                    round(cp_idle_tick_us, 1)
                    if cp_idle_tick_us is not None
                    else None
                ),
                "cp_idle_tick_ok": cp_idle_tick_ok,
                "metrics_scrape_overhead": metrics_scrape_overhead,
                "scrape_share_ok": scrape_share_ok,
                "metrics_query_p99_ok": metrics_query_p99_ok,
                "analysis_runtime_s": (
                    round(analysis_runtime_s, 3)
                    if analysis_runtime_s is not None
                    else None
                ),
                "analysis_runtime_ok": analysis_runtime_ok,
            }
        )
    )


if __name__ == "__main__":
    main()
